"""Metrics/observability: the reference's stdout format + scalar sinks.

The reference's observability is the cadenced print
(``MNISTDist.py:183-186``) and a summary op wired into the Supervisor's
event files (``:155,162`` — though it merges nothing, SURVEY.md §5). Here
the same stdout line is reproduced verbatim-format, and every scalar lands
in BOTH a JSONL file (any plotting tool) and a TensorBoard event file
(utils/events.py — the summary-writer parity path)."""

from __future__ import annotations

import json
import math
import os
import threading
import time

from distributed_tensorflow_tpu.utils.events import EventFileWriter


def reference_log_line(job_name: str, task_index: int, step: int, loss, acc) -> str:
    """The exact print of MNISTDist.py:183-186 (print-function comma
    semantics: single-space join of the arguments)."""
    return " ".join(
        [
            f"job: {job_name}/{task_index}",
            "step: ",
            str(step),
            "mini_batch loss: ",
            str(loss),
            "training accuracy: ",
            str(acc),
        ]
    )


class MetricsLogger:
    """Scalar logger: stdout (reference format) + JSONL + TB event file.

    Thread-safe: the serving metrics cadence (batcher worker threads)
    and a training loop can share one logger — ``scalars`` serializes
    the two sink writes under a lock so JSONL lines and event frames
    never interleave. Every emission also rides the telemetry flight
    ring, so a crash postmortem shows the last scalars next to the last
    spans; ``flush()`` (called at the display cadence and from the
    flight-recorder dump path) pushes both sinks' buffered tails to
    disk so a crash doesn't lose them."""

    def __init__(self, logdir: str | None = None, job_name: str = "worker",
                 task_index: int = 0, filename: str = "metrics.jsonl"):
        self.job_name = job_name or "worker"
        self.task_index = task_index
        self._file = None
        self._events = None
        self._lock = threading.Lock()
        if logdir:
            os.makedirs(logdir, exist_ok=True)
            self._file = open(os.path.join(logdir, filename), "a", buffering=1)
            self._events = EventFileWriter(logdir)
            # flight-recorder dumps flush this logger's tails too
            from distributed_tensorflow_tpu.utils import telemetry

            telemetry.register_flush(self.flush)

    def log_display(self, step: int, loss, acc):
        print(reference_log_line(self.job_name, self.task_index, step, loss, acc))
        self.scalars(step, {"mini_batch_loss": float(loss), "training_accuracy": float(acc)})

    def scalars(self, step: int, values: dict):
        from distributed_tensorflow_tpu.utils import telemetry

        with self._lock:
            if self._file is not None:
                rec = {"step": int(step), "time": time.time(),
                       "job": f"{self.job_name}/{self.task_index}", **values}
                self._file.write(json.dumps(rec) + "\n")
            if self._events is not None:
                self._events.add_scalars(step, values)
        telemetry.record_scalars(step, values)

    def flush(self):
        """Push both sinks' buffered tails to disk (the JSONL file is
        line-buffered, the event writer flushes per frame — this covers
        the residue plus any OS-level buffering before a crash)."""
        with self._lock:
            if self._file is not None:
                self._file.flush()
            if self._events is not None:
                self._events.flush()

    def close(self):
        from distributed_tensorflow_tpu.utils import telemetry

        # run teardown is the last guaranteed flush point: drain the
        # span sink too (the final checkpoint's ckpt_write span lands
        # after the last display-cadence flush)
        telemetry.get_tracer().flush()
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
            if self._events is not None:
                self._events.close()
                self._events = None


class StreamingHistogram:
    """Streaming quantile estimator over geometric buckets (p50/p90/p99).

    The serving path needs latency QUANTILES, not means — a p99 cannot be
    recovered from scalar averages after the fact — but must not hold
    every observation (heavy traffic = millions of samples). Values land
    in geometrically-spaced buckets (``growth`` relative width per
    bucket, so the quantile error is bounded by the bucket ratio, ~4%
    at the default), quantiles read the bucket CDF with log-linear
    interpolation inside the landing bucket. O(1) record, O(buckets)
    quantile, fixed memory. Thread-safe: server handler threads record
    while the metrics cadence reads.

    ``summary(prefix)`` returns the p50/p90/p99/mean/count dict shaped
    for ``MetricsLogger.scalars`` — serving latency lands in the same
    JSONL + TensorBoard event sinks as the training scalars.
    """

    QUANTILES = (0.5, 0.9, 0.99)

    def __init__(self, low: float = 1e-3, high: float = 1e7,
                 growth: float = 1.08):
        if not (0 < low < high) or growth <= 1.0:
            raise ValueError(f"need 0 < low < high and growth > 1, got "
                             f"low={low}, high={high}, growth={growth}")
        self._low = float(low)
        self._log_growth = math.log(growth)
        n = int(math.ceil(math.log(high / low) / self._log_growth))
        # bucket i spans [low*g^i, low*g^(i+1)); +2 for underflow/overflow
        self._counts = [0] * (n + 2)
        self._lock = threading.Lock()
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    def _bucket(self, value: float) -> int:
        if value < self._low:
            return 0
        i = int(math.log(value / self._low) / self._log_growth) + 1
        return min(i, len(self._counts) - 1)

    def record(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._counts[self._bucket(value)] += 1
            self._count += 1
            self._sum += value
            self._min = min(self._min, value)
            self._max = max(self._max, value)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def mean(self) -> float:
        with self._lock:
            return self._sum / self._count if self._count else 0.0

    def _edge(self, i: int) -> float:
        """Lower edge of bucket ``i`` (i >= 1; bucket 0 is underflow)."""
        return self._low * math.exp((i - 1) * self._log_growth)

    def _snapshot(self) -> tuple:
        """One-lock consistent copy of the full estimator state — the
        quantiles, mean and count a reader derives from it can never
        disagree with each other (a cadence read racing ``record`` used
        to take the lock per quantile and read ``_count`` outside it)."""
        with self._lock:
            return (list(self._counts), self._count, self._sum,
                    self._min, self._max)

    def _quantile_from(self, counts, count, mn, mx, q: float) -> float:
        if not count:
            return 0.0
        rank = q * count
        seen = 0.0
        for i, c in enumerate(counts):
            if not c:
                continue
            if seen + c >= rank:
                if i == 0:
                    return mn
                frac = min(max((rank - seen) / c, 0.0), 1.0)
                lo = self._edge(i)
                val = lo * math.exp(frac * self._log_growth)
                return min(max(val, mn), mx)
            seen += c
        return mx

    def quantile(self, q: float) -> float:
        """Value at quantile ``q`` in [0, 1]; 0.0 when empty. Clamped to
        the observed min/max so sparse histograms don't over-report the
        bucket width."""
        counts, count, _total, mn, mx = self._snapshot()
        return self._quantile_from(counts, count, mn, mx, q)

    def summary(self, prefix: str = "") -> dict:
        """{prefix}p50/p90/p99/mean/count — the scalars dict the serving
        metrics cadence hands to MetricsLogger/events. Computed from ONE
        locked snapshot: the count always agrees with the quantiles even
        while handler threads record concurrently."""
        counts, count, total, mn, mx = self._snapshot()
        out = {f"{prefix}p{int(q * 100)}":
               self._quantile_from(counts, count, mn, mx, q)
               for q in self.QUANTILES}
        out[f"{prefix}mean"] = total / count if count else 0.0
        out[f"{prefix}count"] = float(count)
        return out

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * len(self._counts)
            self._count = 0
            self._sum = 0.0
            self._min = math.inf
            self._max = -math.inf
