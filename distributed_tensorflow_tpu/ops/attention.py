"""Attention ops: dense multi-head attention and RING attention for
sequence/context parallelism.

The reference framework predates attention entirely — this module is the
build's long-context extension, designed TPU-first: the sequence axis is
sharded over a mesh axis and the key/value blocks ROTATE around the ring
with ``lax.ppermute`` (one ICI hop per step) while each device's queries
accumulate the streaming-softmax statistics blockwise (the flash/online
softmax recurrence). Peak activation memory per device is one (q, k, v)
block regardless of total sequence length, and the collective traffic
rides neighbor-to-neighbor ICI links — the layout "How to Scale Your
Model"-style context parallelism wants.

Everything is expressed with ``lax.scan`` + differentiable collectives
(``ppermute`` has a transpose rule), so ``jax.grad`` through a ring step
is exact — no custom VJP required. Equivalence with dense attention (fwd
and grads) is pinned by tests/test_attention.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def multi_head_attention(q, k, v, causal: bool = False):
    """Dense (all-to-all) multi-head attention.

    q, k, v: (B, S, H, Dh) -> (B, S, H, Dh). f32 softmax statistics
    regardless of input dtype (bf16-safe). ``causal`` masks j > i (the
    autoregressive/LM form).
    """
    dh = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    s = s / jnp.sqrt(jnp.float32(dh))
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(sk)[None, :] <= jnp.arange(sq)[:, None]
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), v)


def _online_softmax_step(qf, scale, o, m, l, k_blk, v_blk, mask):
    """Fold one k/v block into the streaming-softmax accumulators.

    The one implementation of the flash/online-softmax recurrence, shared
    by ``ring_attention`` (blocks arrive over ICI) and
    ``blockwise_attention`` (blocks are scanned locally): running max m,
    denominator l, unnormalized numerator o, all f32. ``mask`` (broadcast
    to (B, H, Sq, Skb)) or None."""
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32))
    s = s * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m_new = jnp.maximum(m, s.max(axis=-1))
    corr = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l = l * corr + p.sum(axis=-1)
    o = o * corr[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32))
    return o, m_new, l


def blockwise_attention(q, k, v, block_size: int, causal: bool = False):
    """Single-device attention with O(S * block) peak memory.

    Same math as ``multi_head_attention`` (pinned by tests), computed as
    a ``lax.scan`` over k/v blocks with the online-softmax recurrence —
    the full (Sq, Sk) score matrix never materializes, so a long context
    fits one chip's HBM where the dense form would not (peak activation
    is one (B, H, Sq, block) panel instead of (B, H, Sq, Sk)). This is
    the dense/ single-chip half of the long-context story;
    ``ring_attention`` is the same recurrence with blocks arriving over
    the mesh instead of a local scan.

    ``causal=True`` masks by absolute position, identical to the dense
    triangle. Blocks entirely above the diagonal still run (static scan
    length — XLA needs static shapes) but contribute exact zeros; queries
    attend their own block first via the mask, not by reordering, so the
    recurrence stays the plain scan.
    """
    b, sq, h, dh = q.shape
    sk = k.shape[1]
    if sk % block_size:
        raise ValueError(f"key length {sk} must divide into blocks of "
                         f"{block_size}")
    n_blocks = sk // block_size
    scale = 1.0 / jnp.sqrt(jnp.float32(dh))
    qf = q.astype(jnp.float32)
    rows = jnp.arange(sq)
    # scan over key/value blocks: (n_blocks, B, blk, H, Dh)
    kb = jnp.moveaxis(k.reshape(b, n_blocks, block_size, h, dh), 1, 0)
    vb = jnp.moveaxis(v.reshape(b, n_blocks, block_size, h, dh), 1, 0)

    def step(carry, inp):
        o, m, l = carry
        t, k_blk, v_blk = inp
        mask = None
        if causal:
            cols = t * block_size + jnp.arange(block_size)
            mask = (cols[None, :] <= rows[:, None])[None, None]
        o, m, l = _online_softmax_step(qf, scale, o, m, l, k_blk, v_blk,
                                       mask)
        return (o, m, l), None

    o0 = jnp.zeros((b, h, sq, dh), jnp.float32)
    m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    (o, _, l), _ = lax.scan(step, (o0, m0, l0),
                            (jnp.arange(n_blocks), kb, vb))
    out = o / l[..., None]
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def ring_attention(q, k, v, axis_name: str, causal: bool = False):
    """Ring attention over the mesh axis ``axis_name`` (sequence-sharded).

    Call INSIDE shard_map with the sequence dimension of q/k/v sharded
    over ``axis_name``: q, k, v are the LOCAL blocks (B, S/P, H, Dh).
    Each of the P ring steps attends the local queries against the
    currently-held k/v block, folds the result into the online-softmax
    accumulators (running max m, denominator l, numerator o), and passes
    the k/v block to the next device (``ppermute``; P-1 hops — the local
    block is consumed before the scan). After P steps every query has
    seen every key exactly once; the result equals dense attention over
    the gathered sequence (tested to fp tolerance).

    ``causal=True`` masks by GLOBAL token position: at ring step t this
    device holds the k/v block of shard (me - t) mod P, so the mask
    compares (my_shard * Sq + i) against (owner * Sk + j) — the
    blockwise form of the LM triangle. Attending the local block first
    guarantees the running max is finite from step one (the diagonal is
    never masked), so fully-masked later blocks contribute exact zeros.
    """
    p_size = lax.axis_size(axis_name)
    dh = q.shape[-1]
    b, sq, h, _ = q.shape
    scale = 1.0 / jnp.sqrt(jnp.float32(dh))
    # accumulate in f32: the online-softmax recurrence is exact in exact
    # arithmetic; f32 keeps the rescaling stable for bf16 inputs
    qf = q.astype(jnp.float32)
    perm = [(i, (i + 1) % p_size) for i in range(p_size)]
    me = lax.axis_index(axis_name)
    row_global = me * sq + jnp.arange(sq)  # my queries' global positions

    def attend(o, m, l, k_blk, v_blk, owner):
        mask = None
        if causal:
            col_global = owner * k_blk.shape[1] + jnp.arange(k_blk.shape[1])
            mask = (col_global[None, :] <= row_global[:, None])[None, None]
        return _online_softmax_step(qf, scale, o, m, l, k_blk, v_blk, mask)

    def ring_step(carry, t):
        # rotate FIRST, then attend: the locally-held block is consumed
        # outside the scan, so exactly P-1 ICI hops happen (a trailing
        # rotation whose output nobody reads would not be DCE'd out of
        # the compiled loop). After t rotations this device holds the
        # block ORIGINALLY owned by shard (me - t) mod P.
        o, m, l, k_blk, v_blk = carry
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        o, m, l = attend(o, m, l, k_blk, v_blk, (me - t) % p_size)
        return (o, m, l, k_blk, v_blk), None

    o0 = jnp.zeros((b, h, sq, dh), jnp.float32)
    m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    o, m, l = attend(o0, m0, l0, k, v, me)
    (o, _, l, _, _), _ = lax.scan(
        ring_step, (o, m, l, k, v), jnp.arange(1, p_size))
    out = o / l[..., None]
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)
