"""Checkpoint inspection CLI — the ``inspect_checkpoint`` counterpart of
TF's Saver tooling, for this build's npz pytree checkpoints.

    python -m distributed_tensorflow_tpu.checkpoint.inspect --logdir /tmp/train_logs
    python -m distributed_tensorflow_tpu.checkpoint.inspect --path ckpt-1000.npz --key params/weights/wd1
    python -m distributed_tensorflow_tpu.checkpoint.inspect --verify --logdir /tmp/train_logs

Lists every stored array (path key, shape, dtype — bf16-tagged entries
decoded), the global step, and the total parameter count; ``--key`` also
prints one array's statistics. ``--verify`` checksum-checks EVERY set in
a logdir (both formats) against the per-array CRC-32C manifests, reports
ok/CORRUPT/incomplete per step, and exits nonzero if the newest
restorable set is corrupt. Read-only; works on checkpoints from every
mode (full TrainState layouts and ps-mode params-only layouts alike).
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from distributed_tensorflow_tpu.checkpoint.checkpoint import latest_checkpoint
from distributed_tensorflow_tpu.utils.pytree import _BF16_TAG


def load_entries(path: str) -> tuple[dict[str, np.ndarray], set[str]]:
    """({clean_key: array}, undecoded_keys) with bf16-tagged entries decoded
    to float32 (a lossless widening — npz stores them as uint16 views).
    ``undecoded_keys`` names bf16-tagged entries left as raw uint16 views
    because ml_dtypes was unavailable — their values are NOT interpretable
    as numbers. Reads both the monolithic npz and the sharded format
    (any shard file of a complete set reassembles the whole state)."""
    from distributed_tensorflow_tpu.checkpoint.checkpoint import load_flat

    try:
        import ml_dtypes

        bf16 = np.dtype(ml_dtypes.bfloat16)
    except ImportError:  # pragma: no cover — ml_dtypes ships with jax
        bf16 = None
    out = {}
    undecoded = set()
    for k, arr in load_flat(path).items():
        if k.startswith(_BF16_TAG):
            k = k[len(_BF16_TAG):]
            if bf16 is not None:
                arr = arr.view(bf16).astype(np.float32)
            else:
                undecoded.add(k)
        out[k] = arr
    return out, undecoded


def describe(path: str, key: str | None = None, out=None) -> int:
    out = out if out is not None else sys.stdout  # bind at call time
    entries, undecoded = load_entries(path)
    step = entries.get("step")
    print(f"checkpoint: {path}", file=out)
    if step is not None:
        print(f"global step: {int(np.asarray(step))}", file=out)
    total = 0
    for k in sorted(entries):
        if k == "step":
            continue
        a = entries[k]
        total += a.size
        dtype = "bfloat16 (raw bits; no ml_dtypes)" if k in undecoded else a.dtype
        print(f"  {k}  shape={tuple(a.shape)}  dtype={dtype}", file=out)
    print(f"total elements (excl. step): {total:,}", file=out)
    if key is not None:
        if key not in entries:
            print(f"error: no array {key!r} in checkpoint "
                  f"(keys: {sorted(entries)[:8]}...)", file=sys.stderr)
            return 2
        if key in undecoded:
            # the stored array is a raw uint16 view of bf16 bits; stats on
            # it would be meaningless — refuse rather than mislead
            print(f"error: {key!r} is stored as bf16 and ml_dtypes is not "
                  f"available to decode it; install ml_dtypes to print "
                  f"statistics", file=sys.stderr)
            return 2
        a = np.asarray(entries[key], np.float64)
        print(f"{key}: min={a.min():.6g} max={a.max():.6g} "
              f"mean={a.mean():.6g} std={a.std():.6g}", file=out)
    return 0


def verify_logdir(directory: str, out=None) -> int:
    """``--verify``: checksum-check every checkpoint set in ``directory``
    — monolithic files AND sharded sets — through the same load paths
    restore uses (manifest CRC-32C + coverage/mixing checks). Prints one
    line per (step, format): ok / ok (no manifest) / CORRUPT (reason) /
    incomplete (j/n shards). Returns nonzero iff the NEWEST restorable
    set — the one restore would pick first — is corrupt."""
    import os

    from distributed_tensorflow_tpu.checkpoint.checkpoint import (
        _MANIFEST,
        _PREFIX,
        _scan_shards,
        load_flat,
        load_flat_sharded,
    )

    out = out if out is not None else sys.stdout
    if not os.path.isdir(directory):
        print(f"no such directory: {directory}", file=sys.stderr)
        return 1
    complete, all_shards = _scan_shards(directory)
    mono: dict[int, str] = {}
    import re

    for name in os.listdir(directory):
        m = re.fullmatch(rf"{_PREFIX}-(\d+)\.npz", name)
        if m:
            mono[int(m.group(1))] = os.path.join(directory, name)
    quarantined = [n for n in os.listdir(directory) if ".corrupt" in n]
    steps = sorted(set(mono) | set(complete) | set(all_shards))
    if not steps:
        print(f"no checkpoints in {directory}", file=out)
        return 1
    restorable = sorted(set(mono) | set(complete))
    newest = restorable[-1] if restorable else None
    newest_ok = True
    for step in steps:
        if step in mono:
            try:
                with np.load(mono[step]) as z:
                    has_manifest = _MANIFEST in z.files
                load_flat(mono[step])
                status = "ok" if has_manifest else "ok (no manifest)"
            except Exception as e:  # noqa: BLE001 — reported per set
                status = f"CORRUPT ({type(e).__name__}: {e})"
                if step == newest:
                    newest_ok = False
            print(f"step {step} [monolithic]: {status}", file=out)
        if step in complete:
            n = len(complete[step])
            try:
                load_flat_sharded(directory, step)
                status = "ok"
            except Exception as e:  # noqa: BLE001 — reported per set
                status = f"CORRUPT ({type(e).__name__}: {e})"
                if step == newest and step not in mono:
                    newest_ok = False
            print(f"step {step} [sharded x{n}]: {status}", file=out)
        elif step in all_shards and step not in mono:
            print(f"step {step} [sharded]: incomplete "
                  f"({len(all_shards[step])} orphan shard file(s), no "
                  f"complete set)", file=out)
    if quarantined:
        print(f"{len(quarantined)} quarantined *.corrupt file(s) present",
              file=out)
    if not newest_ok:
        print(f"newest restorable set (step {newest}) is CORRUPT — "
              f"restore would quarantine it and fall back", file=out)
        return 1
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Inspect a distributed_tensorflow_tpu checkpoint")
    p.add_argument("--logdir", help="checkpoint directory (inspects the "
                   "latest checkpoint, like restore does)")
    p.add_argument("--path", help="a specific ckpt-N.npz file")
    p.add_argument("--key", help="also print statistics of this array")
    p.add_argument("--verify", action="store_true",
                   help="checksum-check EVERY set in --logdir (both "
                   "formats); nonzero exit if the newest restorable set "
                   "is corrupt")
    args = p.parse_args(argv)
    if args.verify:
        if not args.logdir:
            p.error("--verify requires --logdir")
        return verify_logdir(args.logdir)
    if bool(args.logdir) == bool(args.path):
        p.error("exactly one of --logdir / --path is required")
    path = args.path
    if args.logdir:
        found = latest_checkpoint(args.logdir)
        if found is None:
            print(f"no checkpoint found in {args.logdir}", file=sys.stderr)
            return 1
        path = found[0]
    return describe(path, args.key)


if __name__ == "__main__":
    sys.exit(main())
