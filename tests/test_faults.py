"""Deterministic fault injection + the verified-restore fallback ladder.

The r8 robustness tentpole under test: every failure mode the recovery
code claims to survive is exercised through --fault_spec rules (or direct
file surgery where a machine crash is being forged), and restore is
proven to quarantine the damaged set and walk back instead of crashing
or training on garbage.
"""

import io
import json
import os
import subprocess
import sys
import zipfile

import numpy as np
import pytest

from distributed_tensorflow_tpu.checkpoint.checkpoint import (
    CheckpointCorruptError,
    latest_checkpoint,
    restore_latest,
    restore_with_fallback,
    save_checkpoint,
    save_checkpoint_sharded,
)
from distributed_tensorflow_tpu.utils import faults
from distributed_tensorflow_tpu.utils.events import (
    _crc32c,
    _crc32c_numpy,
    crc32c,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm_faults():
    """Every test starts and ends with no rules armed (and the env-var
    check forgotten), so specs cannot leak between tests."""
    faults.reset()
    yield
    faults.reset()


# ------------------------------------------------------------ spec grammar


def test_spec_parses_the_documented_examples():
    rules = faults.parse_fault_spec(
        "ckpt_write:at_step=40:mode=crash,restore:mode=torn_file,"
        "init:mode=refuse:times=2")
    assert [r.point for r in rules] == ["ckpt_write", "restore", "init"]
    assert rules[0].mode == "crash" and rules[0].at_step == 40
    assert rules[1].mode == "torn_file"
    assert rules[2].mode == "refuse" and rules[2].times == 2


@pytest.mark.parametrize("bad,match", [
    ("bogus:mode=crash", "unknown injection point"),
    ("restore:mode=explode", "unknown mode"),
    ("restore:frequency=2", "unknown key"),
    ("restore:at_step=x", "expected an integer"),
    ("restore:mode", "key=value"),
])
def test_spec_rejects_mistakes_with_the_grammar(bad, match):
    with pytest.raises(faults.FaultSpecError, match=match):
        faults.parse_fault_spec(bad)


def test_flag_validator_rejects_bad_spec_at_parse_time():
    from distributed_tensorflow_tpu import flags

    flags.define_reference_flags()
    flags.FLAGS._reset()
    try:
        with pytest.raises(ValueError, match="--fault_spec"):
            flags.FLAGS._parse(["--fault_spec=nonsense:mode=crash"])
    finally:
        flags.FLAGS._reset()


def test_every_registered_point_is_described():
    text = faults.describe_points()
    for point in faults.INJECTION_POINTS:
        assert point in text


def test_trace_ops_lists_faults():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_ops.py"),
         "--faults"],
        capture_output=True, text=True, timeout=60, cwd=REPO)
    assert r.returncode == 0, r.stderr
    for point in faults.INJECTION_POINTS:
        assert point in r.stdout


# ------------------------------------------------------- firing semantics


def test_fault_point_noop_when_unarmed():
    faults.fault_point("restore", path="/nope", step=1)  # must not raise


def test_error_mode_fires_with_matching_filters():
    faults.configure("prefetch:at_count=2:mode=error")
    faults.fault_point("prefetch", count=0)
    faults.fault_point("prefetch", count=1)
    with pytest.raises(faults.InjectedFault):
        faults.fault_point("prefetch", count=2)
    # times defaults to 1: the same count passing again does not re-fire
    faults.fault_point("prefetch", count=2)


def test_times_and_after_budgets():
    faults.configure("init:mode=refuse:times=2:after=1")
    faults.fault_point("init", attempt=0)  # consumed by after=1
    for _ in range(2):
        with pytest.raises(faults.InjectedFault):
            faults.fault_point("init")
    faults.fault_point("init")  # budget exhausted


def test_env_var_arms_subprocessless_callers(monkeypatch):
    monkeypatch.setenv("DTT_FAULT_SPEC", "ckpt_gc:mode=error")
    faults.reset()  # forget the env check so the var is re-read
    with pytest.raises(faults.InjectedFault):
        faults.fault_point("ckpt_gc")


def test_torn_file_mode_truncates_named_file(tmp_path):
    p = tmp_path / "x.bin"
    p.write_bytes(b"a" * 100)
    faults.configure("restore:mode=torn_file")
    faults.fault_point("restore", path=str(p), step=1)
    assert p.stat().st_size == 50


# ------------------------------------------------------------------ crc32c


def test_crc32c_check_value_and_numpy_path_match_scalar():
    # the CRC-32C standard check value
    assert _crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"123456789") == 0xE3069283
    rng = np.random.default_rng(0)
    for n in (0, 1, 17, 1023, 1024, 1025, 4096, 100_000):
        data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
        want = _crc32c(data)
        assert crc32c(data) == want, n
        assert _crc32c_numpy(np.frombuffer(data, np.uint8)) == want, n


def test_crc32c_accepts_ndarrays_any_dtype():
    a = np.arange(1000, dtype=np.float32).reshape(10, 100)
    assert crc32c(a) == crc32c(a.tobytes())


# --------------------------------------------- the verified-restore ladder


def _flip_member_byte(path: str, member_suffix: str = ".npy"):
    """Flip one bit INSIDE a stored array's data region (zip padding and
    headers would shrug a random flip off — this aims at the payload)."""
    with zipfile.ZipFile(path) as z:
        info = max((i for i in z.infolist()
                    if i.filename.endswith(member_suffix)),
                   key=lambda i: i.file_size)
        with open(path, "rb") as f:
            f.seek(info.header_offset)
            hdr = f.read(30)
        name_len = int.from_bytes(hdr[26:28], "little")
        extra_len = int.from_bytes(hdr[28:30], "little")
        # past the .npy magic/header into the raw array bytes
        data_off = (info.header_offset + 30 + name_len + extra_len
                    + min(256, info.file_size - 1))
    with open(path, "r+b") as f:
        f.seek(data_off)
        b = f.read(1)
        f.seek(data_off)
        f.write(bytes([b[0] ^ 0x01]))


def _template():
    return {"params": {"w": np.zeros(512, np.float32),
                       "b": np.zeros(16, np.float32)},
            "step": np.int64(0)}


def _state(step: int, fill: float = 1.0):
    return {"params": {"w": np.full(512, fill, np.float32),
                       "b": np.full(16, fill, np.float32)},
            "step": np.int64(step)}


def test_torn_newest_monolithic_quarantines_and_falls_back(tmp_path):
    d = str(tmp_path)
    save_checkpoint(d, _state(10, 1.0), 10)
    save_checkpoint(d, _state(20, 2.0), 20)
    p = os.path.join(d, "ckpt-20.npz")
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)
    state, step, report = restore_with_fallback(d, _template())
    assert step == 10
    np.testing.assert_array_equal(state["params"]["w"],
                                  np.full(512, 1.0, np.float32))
    assert report.fallback_depth == 1
    assert len(report.quarantined) == 1
    assert report.quarantined[0].endswith(".corrupt")
    assert report.time_s >= 0
    # the corrupt set is invisible to selection AND still on disk
    assert latest_checkpoint(d)[1] == 10
    assert os.path.exists(p + ".corrupt") and not os.path.exists(p)


def test_bitflipped_newest_monolithic_detected_and_skipped(tmp_path):
    d = str(tmp_path)
    save_checkpoint(d, _state(10, 1.0), 10)
    save_checkpoint(d, _state(20, 2.0), 20)
    _flip_member_byte(os.path.join(d, "ckpt-20.npz"))
    state, step, report = restore_with_fallback(d, _template())
    assert step == 10 and report.fallback_depth == 1


def test_zero_length_newest_detected_and_skipped(tmp_path):
    d = str(tmp_path)
    save_checkpoint(d, _state(10), 10)
    save_checkpoint(d, _state(20), 20)
    open(os.path.join(d, "ckpt-20.npz"), "wb").close()
    _, step, report = restore_with_fallback(d, _template())
    assert step == 10 and len(report.quarantined) == 1


def test_torn_newest_sharded_set_quarantines_and_falls_back(tmp_path):
    import glob

    d = str(tmp_path)
    save_checkpoint_sharded(d, _state(10, 1.0), 10)
    save_checkpoint_sharded(d, _state(20, 2.0), 20)
    p = glob.glob(os.path.join(d, "ckpt-20.shard0-of-1*.npz"))[0]
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)
    state, step, report = restore_with_fallback(d, _template())
    assert step == 10 and report.fallback_depth == 1
    np.testing.assert_array_equal(state["params"]["w"],
                                  np.full(512, 1.0, np.float32))
    assert os.path.exists(p + ".corrupt")


def test_bitflipped_sharded_shard_fails_crc_and_falls_back(tmp_path):
    import glob

    d = str(tmp_path)
    save_checkpoint_sharded(d, _state(10, 1.0), 10)
    save_checkpoint_sharded(d, _state(20, 2.0), 20)
    _flip_member_byte(glob.glob(
        os.path.join(d, "ckpt-20.shard0-of-1*.npz"))[0])
    _, step, report = restore_with_fallback(d, _template())
    assert step == 10 and report.fallback_depth == 1


def test_mixed_coverage_set_quarantined(tmp_path):
    """A forged set whose entries overlap (the mixed-save-attempt
    signature load_flat_sharded detects positionally) is quarantined by
    the ladder, not a crash."""
    import glob

    d = str(tmp_path)
    save_checkpoint_sharded(d, {"w": np.arange(4.0, dtype=np.float32)},
                            step=3)
    path = save_checkpoint_sharded(
        d, {"w": np.arange(4.0, dtype=np.float32)}, step=9,
        attempt="cafecafe")
    from distributed_tensorflow_tpu.checkpoint.checkpoint import _SHARDMETA

    with np.load(path) as z:
        meta = json.loads(bytes(z[_SHARDMETA]).decode())
        arrays = {k: z[k] for k in z.files if k != _SHARDMETA}
    (e,) = meta["leaves"]["w"]["entries"]
    e2 = dict(e, npz="w@1")
    e["index"] = [[0, 2]]
    e2["index"] = [[0, 2]]
    meta["leaves"]["w"]["entries"] = [e, e2]
    arrays["w@1"] = arrays[e["npz"]][:2].copy()
    arrays[e["npz"]] = arrays[e["npz"]][:2].copy()
    meta["crc32c"] = {k: crc32c(np.ascontiguousarray(v))
                      for k, v in arrays.items()}
    arrays[_SHARDMETA] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8)
    np.savez(path, **arrays)
    _, step, report = restore_with_fallback(
        d, {"w": np.zeros(4, np.float32)})
    assert step == 3 and report.fallback_depth == 1
    assert len(report.quarantined) == 1


def test_rotted_npy_member_header_quarantined_not_loud(tmp_path):
    """Bit rot in a member's ~100-byte .npy header makes numpy raise a
    bare ValueError ('magic string is not correct') before any CRC runs
    — decode-phase ValueErrors must take the quarantine rung, not crash
    the ladder (r8 review)."""
    d = str(tmp_path)
    save_checkpoint(d, _state(10, 1.0), 10)
    save_checkpoint(d, _state(20, 2.0), 20)
    p = os.path.join(d, "ckpt-20.npz")
    with zipfile.ZipFile(p) as z:
        info = next(i for i in z.infolist() if i.filename.endswith(".npy"))
    with open(p, "r+b") as f:
        f.seek(info.header_offset)
        hdr = f.read(30)
        name_len = int.from_bytes(hdr[26:28], "little")
        extra_len = int.from_bytes(hdr[28:30], "little")
        f.seek(info.header_offset + 30 + name_len + extra_len)
        f.write(b"\x00\x00\x00\x00")  # clobber the \x93NUMPY magic
    _, step, report = restore_with_fallback(d, _template())
    assert step == 10 and report.fallback_depth == 1


def test_losing_the_quarantine_race_falls_back_not_dies(tmp_path,
                                                        monkeypatch):
    """Shared-logdir race: a PEER quarantined (or GC'd) the corrupt set
    between our failed read and our rename — quarantine_step returns []
    but the set is gone, so the ladder must fall back like the race
    winner did, not re-raise (r8 review)."""
    import distributed_tensorflow_tpu.checkpoint.checkpoint as ckpt_mod

    d = str(tmp_path)
    save_checkpoint(d, _state(10, 1.0), 10)
    save_checkpoint(d, _state(20, 2.0), 20)
    p = os.path.join(d, "ckpt-20.npz")
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)

    def peer_wins(directory, step):
        # the peer's rename lands first; ours finds nothing to move
        if os.path.exists(p):
            os.replace(p, p + ".corrupt")
        return []

    monkeypatch.setattr(ckpt_mod, "quarantine_step", peer_wins)
    _, step, report = restore_with_fallback(d, _template())
    assert step == 10
    assert report.fallback_depth == 1 and report.quarantined == ()


def test_newer_format_version_stays_loud_not_quarantined(tmp_path):
    """A shard set from a NEWER build (format version ahead of ours) is
    an intact file this build can't read — the ladder must raise, not
    quarantine a perfectly good checkpoint (r8 review)."""
    import glob

    from distributed_tensorflow_tpu.checkpoint.checkpoint import (
        _SHARDMETA,
        CheckpointFormatError,
    )

    d = str(tmp_path)
    save_checkpoint_sharded(d, {"w": np.arange(4.0, dtype=np.float32)},
                            step=5)
    p = glob.glob(os.path.join(d, "ckpt-5.shard0-of-1*.npz"))[0]
    with np.load(p) as z:
        meta = json.loads(bytes(z[_SHARDMETA]).decode())
        arrays = {k: z[k] for k in z.files if k != _SHARDMETA}
    meta["version"] = 99
    arrays[_SHARDMETA] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8)
    np.savez(p, **arrays)
    with pytest.raises(CheckpointFormatError):
        restore_with_fallback(d, {"w": np.zeros(4, np.float32)})
    assert os.path.exists(p)  # untouched


def test_ladder_exhausted_raises_never_fresh_init(tmp_path):
    d = str(tmp_path)
    for s in (10, 20):
        save_checkpoint(d, _state(s), s)
        p = os.path.join(d, f"ckpt-{s}.npz")
        with open(p, "r+b") as f:
            f.truncate(os.path.getsize(p) // 2)
    with pytest.raises(CheckpointCorruptError, match="no restorable"):
        restore_with_fallback(d, _template())


def test_empty_dir_is_fresh_init_not_an_error(tmp_path):
    assert restore_with_fallback(str(tmp_path / "none"), _template()) is None


def test_structural_mismatch_stays_loud_not_quarantined(tmp_path):
    """A checkpoint that is INTACT but doesn't fit the template (wrong
    layout) must raise immediately — falling back would resurrect an old
    trajectory under a changed config — and must NOT be quarantined."""
    d = str(tmp_path)
    save_checkpoint(d, _state(10), 10)
    bad_template = {"params": {"w": np.zeros(512, np.float32),
                               "b": np.zeros(16, np.float32),
                               "extra": np.zeros(3, np.float32)},
                    "step": np.int64(0)}
    with pytest.raises(KeyError, match="extra"):
        restore_with_fallback(d, bad_template)
    assert os.path.exists(os.path.join(d, "ckpt-10.npz"))  # untouched


def test_manifestless_legacy_checkpoint_still_restores(tmp_path):
    """Pre-manifest files (older saves) restore unverified — the format
    change is backward compatible."""
    d = str(tmp_path)
    np.savez(os.path.join(d, "ckpt-5.npz"),
             **{"params/w": np.full(512, 3.0, np.float32),
                "params/b": np.full(16, 3.0, np.float32),
                "step": np.int64(5)})
    state, step = restore_latest(d, _template())
    assert step == 5
    np.testing.assert_array_equal(state["params"]["w"],
                                  np.full(512, 3.0, np.float32))
    out = restore_with_fallback(d, _template())
    assert out is not None and out[1] == 5 and out[2].fallback_depth == 0


def test_restore_injection_one_liner_drives_the_ladder(tmp_path):
    """The tentpole's point: `--fault_spec restore:mode=torn_file` is the
    whole reproduction of a torn newest checkpoint."""
    d = str(tmp_path)
    save_checkpoint(d, _state(10, 1.0), 10)
    save_checkpoint(d, _state(20, 2.0), 20)
    faults.configure("restore:mode=torn_file:times=1")
    _, step, report = restore_with_fallback(d, _template())
    assert step == 10 and report.fallback_depth == 1


def test_gc_accounting_ignores_quarantined_files(tmp_path):
    """Quarantined sets neither count toward max_to_keep nor get
    deleted."""
    from distributed_tensorflow_tpu.checkpoint.checkpoint import _gc

    d = str(tmp_path)
    for s in (1, 2, 3):
        save_checkpoint(d, _state(s), s, max_to_keep=10)
    p = os.path.join(d, "ckpt-3.npz")
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)
    _, step, _ = restore_with_fallback(d, _template())
    assert step == 2
    _gc(d, max_to_keep=2)
    names = set(os.listdir(d))
    assert "ckpt-3.npz.corrupt" in names  # survives GC forever
    assert "ckpt-1.npz" in names and "ckpt-2.npz" in names  # 2 kept


# ----------------------------------------------------- supervisor wiring


def test_supervisor_restores_through_the_ladder(tmp_path):
    from distributed_tensorflow_tpu.models import DeepCNN
    from distributed_tensorflow_tpu.training import create_train_state, sgd
    from distributed_tensorflow_tpu.training.supervisor import Supervisor

    d = str(tmp_path)
    state = create_train_state(DeepCNN(), sgd(0.01), seed=0)
    save_checkpoint(d, state, 10)
    save_checkpoint(d, state, 20)
    p = os.path.join(d, "ckpt-20.npz")
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)
    sv = Supervisor(is_chief=True, logdir=d, save_model_secs=10_000)
    restored, step = sv.init_or_restore(state)
    assert step == 10
    rep = sv.restore_report
    assert rep is not None and rep.step == 10
    assert rep.fallback_depth == 1 and len(rep.quarantined) == 1


def test_supervisor_fresh_init_has_no_report(tmp_path):
    from distributed_tensorflow_tpu.models import DeepCNN
    from distributed_tensorflow_tpu.training import create_train_state, sgd
    from distributed_tensorflow_tpu.training.supervisor import Supervisor

    sv = Supervisor(is_chief=True, logdir=str(tmp_path),
                    save_model_secs=10_000)
    state = create_train_state(DeepCNN(), sgd(0.01), seed=0)
    _, step = sv.init_or_restore(state)
    assert step == 0 and sv.restore_report is None


def test_exit_agreement_injection_fails_the_agreement():
    """exit_agreement:mode=error — the agreement's bounded gather fails,
    the verdict comes back None (managed() then skips the final save and
    raises the abandoned error on a clean exit): the r3 exit protocol
    exercised deterministically, single-process."""
    from distributed_tensorflow_tpu.utils.pytree import agree_clean_exit

    faults.configure("exit_agreement:mode=error")
    verdict, token = agree_clean_exit(True, timeout_s=30.0,
                                      return_token=True)
    assert verdict is None and token is None


def test_collective_fetch_injection_reports_failed_final_save(tmp_path,
                                                              capsys):
    """collective_fetch:mode=error — the exit save fails LOUDLY but the
    managed() exit still completes (best-effort final save contract)."""
    from distributed_tensorflow_tpu.models import DeepCNN
    from distributed_tensorflow_tpu.training import create_train_state, sgd
    from distributed_tensorflow_tpu.training.supervisor import Supervisor

    state = create_train_state(DeepCNN(), sgd(0.01), seed=0)
    faults.configure("collective_fetch:mode=error")
    sv = Supervisor(is_chief=True, logdir=str(tmp_path),
                    save_model_secs=10_000)
    with sv.managed(state) as box:
        box.update(state, 3)
    assert "final checkpoint failed" in capsys.readouterr().out
    assert latest_checkpoint(str(tmp_path)) is None


def test_ckpt_write_crash_mode_hard_exits_subprocess(tmp_path):
    """ckpt_write:mode=crash is a hard os._exit(17): no final save, no
    atexit — but the file ALREADY landed (the point fires after the
    atomic rename), so a restart restores it through the index-fallback
    scan even though the index write never happened."""
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import numpy as np\n"
        "from distributed_tensorflow_tpu.utils import faults\n"
        "from distributed_tensorflow_tpu.checkpoint.checkpoint import "
        "save_checkpoint\n"
        "faults.configure('ckpt_write:at_step=7:mode=crash')\n"
        f"d = {str(tmp_path)!r}\n"
        "save_checkpoint(d, {'w': np.arange(4.0, dtype=np.float32)}, 3)\n"
        "save_checkpoint(d, {'w': np.arange(4.0, dtype=np.float32)}, 7)\n"
        "print('NOT REACHED')\n"
    )
    r = subprocess.run([sys.executable, "-c", code],
                       env={**os.environ, "PYTHONPATH": REPO,
                            "JAX_PLATFORMS": "cpu"},
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == faults.FAULT_EXIT_CODE, r.stdout + r.stderr
    assert "NOT REACHED" not in r.stdout
    assert os.path.exists(tmp_path / "ckpt-7.npz")
    # the index still names step 3 (the crash beat the index write) but
    # selection is scan-based, so the newer complete file wins
    found = latest_checkpoint(str(tmp_path))
    assert found is not None and found[1] == 7
    out = restore_with_fallback(str(tmp_path),
                                {"w": np.zeros(4, np.float32)})
    assert out is not None and out[1] == 7


# ------------------------------------------------------- init retry path


def test_init_retry_rides_through_injected_refusals():
    from distributed_tensorflow_tpu.cluster import _initialize_with_retry

    faults.configure("init:mode=refuse:times=2")
    calls = {"n": 0}
    sleeps = []

    def init_fn():
        calls["n"] += 1

    _initialize_with_retry(init_fn, retries=3, backoff_s=0.5,
                           what="test init", sleep=sleeps.append)
    assert calls["n"] == 1  # two injected refusals, then the real join
    assert sleeps == [0.5, 1.0]  # linear backoff


def test_init_retry_exhausts_loudly():
    from distributed_tensorflow_tpu.cluster import _initialize_with_retry

    faults.configure("init:mode=refuse:times=0")  # unlimited refusals
    with pytest.raises(faults.InjectedFault):
        _initialize_with_retry(lambda: None, retries=2, backoff_s=0.1,
                               what="test init", sleep=lambda s: None)


def test_init_retry_runs_cleanup_between_attempts():
    from distributed_tensorflow_tpu.cluster import _initialize_with_retry

    faults.configure("init:mode=refuse:times=1")
    cleaned = {"n": 0}
    _initialize_with_retry(lambda: None, retries=2, backoff_s=0.0,
                           what="test init", sleep=lambda s: None,
                           cleanup_fn=lambda: cleaned.update(
                               n=cleaned["n"] + 1))
    assert cleaned["n"] == 1


def test_maybe_initialize_skips_single_host():
    from distributed_tensorflow_tpu.cluster import (
        ClusterSpec,
        maybe_initialize_distributed,
    )

    spec = ClusterSpec({"ps": [], "worker": ["localhost:1"]})
    assert maybe_initialize_distributed(spec, 0, init_retries=5) is False


# -------------------------------------------------- bench recovery fields


def test_bench_recovery_phase_nonnull():
    import bench

    out = bench.recovery_phase()
    assert out["recovery_restore_step"] == 10
    assert out["recovery_fallback_depth"] == 1
    assert out["recovery_quarantined"] == 1
    assert out["recovery_time_s"] is not None


def test_bench_degraded_record_keeps_recovery_fields():
    import bench

    rec = bench.degraded_record("forced outage", {"attempts": 1},
                                cpu_smoke=False)
    assert rec["recovery_restore_step"] == 10
    assert rec["recovery_fallback_depth"] == 1
    assert rec["recovery_time_s"] is not None


# --------------------------------------------------------- inspect --verify


def test_inspect_verify_reports_and_exit_code(tmp_path):
    from distributed_tensorflow_tpu.checkpoint.inspect import (
        main as inspect_main,
        verify_logdir,
    )

    d = str(tmp_path)
    save_checkpoint(d, _state(10), 10)
    save_checkpoint_sharded(d, _state(20), 20)
    buf = io.StringIO()
    assert verify_logdir(d, out=buf) == 0
    text = buf.getvalue()
    assert "step 10 [monolithic]: ok" in text
    assert "step 20 [sharded x1]: ok" in text
    # tear the newest -> nonzero + CORRUPT line
    import glob

    p = glob.glob(os.path.join(d, "ckpt-20.shard0-of-1*.npz"))[0]
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)
    buf = io.StringIO()
    assert verify_logdir(d, out=buf) == 1
    text = buf.getvalue()
    assert "CORRUPT" in text and "newest restorable set" in text
    # older-set corruption alone does not fail the exit code
    os.replace(p, p + ".gone")  # leave only an orphaned... restore it
    os.replace(p + ".gone", p)
    save_checkpoint(d, _state(30), 30)
    buf = io.StringIO()
    assert verify_logdir(d, out=buf) == 0, buf.getvalue()
    # CLI surface
    assert inspect_main(["--verify", f"--logdir={d}"]) == 0


def test_inspect_verify_flags_incomplete_sets(tmp_path):
    import glob
    import shutil

    from distributed_tensorflow_tpu.checkpoint.inspect import verify_logdir

    d = str(tmp_path)
    save_checkpoint_sharded(d, _state(5), 5)
    src = glob.glob(os.path.join(d, "ckpt-5.shard0-of-1*.npz"))[0]
    shutil.copy(src, os.path.join(d, "ckpt-9.shard0-of-2.npz"))
    buf = io.StringIO()
    verify_rc = verify_logdir(d, out=buf)
    assert "step 9 [sharded]: incomplete" in buf.getvalue()
    assert verify_rc == 0  # newest RESTORABLE (step 5) is fine


def test_inspect_verify_notes_manifestless_sets(tmp_path):
    from distributed_tensorflow_tpu.checkpoint.inspect import verify_logdir

    d = str(tmp_path)
    np.savez(os.path.join(d, "ckpt-5.npz"),
             **{"w": np.arange(4.0), "step": np.int64(5)})
    buf = io.StringIO()
    assert verify_logdir(d, out=buf) == 0
    assert "ok (no manifest)" in buf.getvalue()
