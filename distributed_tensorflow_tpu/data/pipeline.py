"""Host→device input pipeline.

The reference uploads each feed_dict batch synchronously inside ``sess.run``
(``MNISTDist.py:179,188``) — host transfer sits on the critical path. The
TPU-native pipeline overlaps instead: a background thread stages the next
batch onto the device (optionally already laid out with a sharding) while
the current step runs, so the accelerator never waits on the host for a
3 M-param model.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator

import jax


def batch_iterator(dataset, batch_size: int, raw: bool = False) -> Iterator:
    """Endless minibatch stream; ``raw=True`` yields thin-wire (uint8,
    int32) batches (see DataSet.next_batch_raw)."""
    draw = dataset.next_batch_raw if raw else dataset.next_batch
    while True:
        yield draw(batch_size)


_END = object()


def prefetch_to_device(
    it: Iterator, size: int = 2, sharding=None, stage: Callable | None = None
) -> Iterator:
    """Wrap a host batch iterator with a device-prefetch queue of ``size``.

    With ``sharding`` (a jax.sharding.Sharding), batches land on the mesh
    pre-sharded (e.g. split on the 'data' axis) so the jitted step never
    reshuffles input layout. ``stage`` overrides the placement entirely
    (e.g. ``shard_batch`` for multi-process global-array assembly).

    Worker exceptions propagate to the consumer (no silent end-of-stream),
    and closing the generator (break / .close()) unblocks and terminates
    the worker thread rather than leaking it on a full queue.

    On the XLA:CPU backend the device placement happens on the CONSUMER
    thread, not the worker: a background thread touching device APIs
    while the main thread dispatches multi-device programs can deadlock
    XLA:CPU's collective rendezvous (the device threads interleave
    programs in different orders — PERF.md; observed as a hang in
    ``block_until_ready`` under load). The worker then only assembles
    host batches. TPU streams execute in enqueue order per chip, so the
    worker stages directly there and the host->device copy overlaps the
    running step — the behavior this pipeline exists for.
    """
    q: queue.Queue = queue.Queue(maxsize=size)
    stop = threading.Event()

    if stage is None:
        def stage(batch):
            if sharding is not None:
                return jax.device_put(batch, sharding)
            return jax.device_put(batch)

    stage_on_worker = jax.default_backend() != "cpu"

    def _send(item) -> bool:
        """put that gives up when the consumer has stopped."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _worker():
        from distributed_tensorflow_tpu.utils.faults import fault_point
        from distributed_tensorflow_tpu.utils.telemetry import trace_span

        try:
            for count, batch in enumerate(it):
                # injection seam for worker-death semantics: an exception
                # here must reach the consumer as that exception — not a
                # hang and not a silent short epoch
                fault_point("prefetch", count=count)
                if stage_on_worker:
                    with trace_span("prefetch_stage", count=count):
                        item = stage(batch)
                else:
                    item = batch
                if stop.is_set() or not _send(item):
                    return
            _send(_END)
        except BaseException as e:  # noqa: BLE001 — delivered to the consumer
            _send(e)

    t = threading.Thread(target=_worker, daemon=True)
    t.start()
    # bound locally: module globals (queue.Empty) may already be torn down
    # when a leaked generator is finalized at interpreter shutdown
    empty_exc = queue.Empty
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item if stage_on_worker else stage(item)
    finally:
        stop.set()
        # drain so a blocked worker sees stop promptly
        try:
            while True:
                q.get_nowait()
        except empty_exc:
            pass
