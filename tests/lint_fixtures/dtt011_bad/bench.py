"""DTT011 bad fixture: three public bench phases — one fact-covered
(quiet), one in neither table (finding), one exempted with a bare
non-string reason (finding)."""


def covered_phase() -> dict:
    return {"covered_total": 1}


def uncovered_phase() -> dict:
    return {"uncovered_rate": 2.0}


def bare_exempt_phase() -> dict:
    return {"bare_rate": 3.0}


def _private_helper_phase() -> dict:  # private: out of scope
    return {}
