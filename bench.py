#!/usr/bin/env python
"""Benchmark: MNIST images/sec/chip on the flagship deep CNN.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Method: sync training over every local chip (mesh + pmean — the framework's
default mode), input pipeline included (host batches staged through the
device-prefetch queue), bf16 matmul/conv compute with f32 master params
(the TPU MXU accumulates bf16 products in f32 in hardware). Warmup step
excluded; steady-state window timed.

vs_baseline: the reference publishes no numbers (BASELINE.md), so the
denominator is the throughput its own defaults *imply* for the north-star
target — 10,000 iterations x batch 128 in <60 s on a v4-8 (8 chips) =>
128*10000/60/8 ~= 2,667 images/sec/chip. value/2667 > 1 means this build
clears the reference's implied per-chip rate.
"""

import json
import time

import jax
import jax.numpy as jnp

IMPLIED_BASELINE_IMAGES_PER_SEC_PER_CHIP = 128 * 10_000 / 60.0 / 8


def main():
    from distributed_tensorflow_tpu.data import read_data_sets
    from distributed_tensorflow_tpu.data.pipeline import batch_iterator, prefetch_to_device
    from distributed_tensorflow_tpu.models import DeepCNN
    from distributed_tensorflow_tpu.parallel import (
        make_dp_train_step,
        make_mesh,
        batch_sharding,
    )
    from distributed_tensorflow_tpu.parallel.data_parallel import replicate_state
    from distributed_tensorflow_tpu.training import adam, create_train_state

    devices = jax.devices()
    n_chips = len(devices)
    batch_size = 128 * max(n_chips // 8, 1) * 8 if n_chips > 1 else 128
    # keep per-chip batch >= 16 and divisible
    while batch_size % n_chips:
        batch_size += 1

    ds = read_data_sets("/tmp/mnist-data", one_hot=True)
    model = DeepCNN(compute_dtype=jnp.bfloat16)
    opt = adam(1e-3)

    if n_chips > 1:
        mesh = make_mesh()
        state = replicate_state(mesh, create_train_state(model, opt, seed=0))
        step_fn = make_dp_train_step(model, opt, mesh, keep_prob=0.75)
        sharding = batch_sharding(mesh, 2)
    else:
        from distributed_tensorflow_tpu.training import make_train_step

        state = create_train_state(model, opt, seed=0)
        step_fn = make_train_step(model, opt, keep_prob=0.75)
        sharding = None

    it = prefetch_to_device(batch_iterator(ds.train, batch_size), size=3,
                            sharding=sharding)
    # warmup (compile)
    state, _ = step_fn(state, next(it))
    jax.block_until_ready(state.params)

    n_steps = 200
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = step_fn(state, next(it))
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0

    images_per_sec = n_steps * batch_size / dt
    per_chip = images_per_sec / n_chips
    print(json.dumps({
        "metric": "mnist_images_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / IMPLIED_BASELINE_IMAGES_PER_SEC_PER_CHIP, 3),
    }))


if __name__ == "__main__":
    main()
