"""DTT007 violating fixture: host impurities inside traced bodies."""

import time

import jax
import numpy as np
from jax import lax


def make_step(xs):
    def body(carry, x):
        if x:  # host branch on a traced value
            carry = carry + 1
        print("step")  # host I/O at trace time only
        t = time.time()  # frozen at trace time
        noise = np.random.rand()  # drawn once, baked into the program
        return carry + t + noise, x

    return lax.scan(body, 0, xs)


@jax.jit
def apply(a):
    print(a)  # trace-time only
    return a * 2
