"""DTT005 conforming fixture: literal, conditional-variable and
parameterized span names, all in the table."""


def run(step, zb, point, tracer):
    with trace_span("good_span", step=step):  # noqa: F821
        pass
    name = "cond_a" if zb else "cond_b"
    with trace_span(name, step=step):  # noqa: F821
        pass
    tracer.record_instant(f"fault:{point}", step=step)
