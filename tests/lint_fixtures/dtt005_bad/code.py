"""DTT005 violating fixture: an undocumented span name (rogue_span)
plus the doc table's ghost_span with no site — drift both ways."""


def run(step):
    with trace_span("good_span", step=step):  # noqa: F821
        pass
    with trace_span("rogue_span", step=step):  # noqa: F821
        pass
