"""Dataset objects with the reference's input-data semantics.

The reference's input pipeline (``MNISTDist.py:167,178``) is
``input_data.read_data_sets(FLAGS.data_dir, one_hot=True)`` + per-worker
``mnist.train.next_batch(batch_size)``: every worker loads the full dataset
and draws its own independently-shuffled minibatches (no inter-worker
sharding). ``DataSet``/``read_data_sets`` reproduce that API and semantics;
``DataSet.shard`` adds the TPU-idiomatic alternative (disjoint shards for
synchronous data-parallel).

Sources, in priority order:
1. IDX files in ``data_dir`` (what the TF tutorial downloader leaves there)
2. CIFAR-10 python pickle batches in ``data_dir`` (for dataset="cifar10")
3. deterministic procedural fallback (offline environments; see synthetic.py)
"""

from __future__ import annotations

import os
import pickle
from dataclasses import dataclass, field

import numpy as np

from distributed_tensorflow_tpu.data import synthetic
from distributed_tensorflow_tpu.data.idx import find_idx_file, read_idx

_MNIST_FILES = {
    "train_images": "train-images-idx3-ubyte",
    "train_labels": "train-labels-idx1-ubyte",
    "test_images": "t10k-images-idx3-ubyte",
    "test_labels": "t10k-labels-idx1-ubyte",
}

SYNTHETIC_TRAIN = 20000
SYNTHETIC_TEST = 2000
LM_TRAIN = 4096  # lm split sizes: sequences are procedural, fresh-
LM_TEST = 512    # permutation-per-row; memorization is impossible anyway


class DataSet:
    """One split. ``next_batch`` matches the reference tutorial DataSet:
    shuffled epochs, each worker shuffles independently from its seed.

    Images may be float32 (already normalized) or uint8: uint8 storage
    keeps the dataset at 1/4 the memory and batches are assembled on
    demand — through the native C++ gather (distributed_tensorflow_tpu.
    native) when its library is built, else NumPy."""

    def __init__(self, images: np.ndarray, labels: np.ndarray, *, one_hot: bool = True,
                 num_classes: int = 10, seed: int = 0):
        assert images.shape[0] == labels.shape[0]
        if images.dtype == np.uint8:
            self._images_u8 = images.reshape(len(images), -1)
            self._images_f32: np.ndarray | None = None
        else:
            self._images_u8 = None
            self._images_f32 = images
        self.labels_int = labels.astype(np.int64)
        # Fail loudly on out-of-range class ids HERE, at load time: the
        # TPU-form cross-entropy one-hots integer labels, and
        # jax.nn.one_hot maps an invalid id to an all-zero row — a
        # corrupt loader would silently train with those examples
        # dropped from the loss (ADVICE r3). One O(n) host check at
        # construction beats a per-step device check.
        bad = (self.labels_int < 0) | (self.labels_int >= num_classes)
        if bad.any():
            idx = int(np.argmax(bad))
            raise ValueError(
                f"label out of range: labels[{idx}] = "
                f"{int(self.labels_int[idx])} not in [0, {num_classes}) "
                f"({int(bad.sum())} invalid of {len(self.labels_int)})")
        self.one_hot = one_hot
        self.num_classes = num_classes
        self._rng = np.random.default_rng(seed)
        self._order = self._fresh_order(images.shape[0])
        self._pos = 0
        self.epochs_completed = 0

    def _fresh_order(self, n: int) -> np.ndarray:
        """Epoch shuffle order. The permutation itself runs in the native
        C++ data plane (Fisher-Yates, fastdata.cpp) when the library is
        built, NumPy otherwise; each epoch's sub-seed is drawn from this
        DataSet's seeded generator either way, so the stream is
        deterministic per (seed, backend)."""
        from distributed_tensorflow_tpu import native

        sub_seed = int(self._rng.integers(0, 2**63 - 1))
        order = native.permutation(n, sub_seed)
        if order is None:
            order = np.random.default_rng(sub_seed).permutation(n)
        return order

    @property
    def images(self) -> np.ndarray:
        """Full split as float32 in [0,1] (materialized once for u8 storage)."""
        if self._images_f32 is None:
            self._images_f32 = self._images_u8.astype(np.float32) / 255.0
        return self._images_f32

    @property
    def num_examples(self) -> int:
        return len(self.labels_int)

    @property
    def labels(self) -> np.ndarray:
        if self.one_hot:
            out = np.zeros((len(self.labels_int), self.num_classes), np.float32)
            out[np.arange(len(self.labels_int)), self.labels_int] = 1.0
            return out
        return self.labels_int

    def _next_indices(self, batch_size: int) -> np.ndarray:
        """Sequential walk over a shuffled order, reshuffling each epoch —
        the tutorial ``DataSet.next_batch`` index behavior the reference's
        hot loop relies on (``MNISTDist.py:178``)."""
        if self.num_examples == 0:
            raise ValueError("next_batch on an empty DataSet (0 examples)")
        idx = np.empty(batch_size, dtype=np.int64)
        filled = 0
        while filled < batch_size:
            take = min(batch_size - filled, len(self._order) - self._pos)
            idx[filled : filled + take] = self._order[self._pos : self._pos + take]
            self._pos += take
            filled += take
            if self._pos >= len(self._order):
                self._order = self._fresh_order(self.num_examples)
                self._pos = 0
                self.epochs_completed += 1
        return idx

    def next_batch(self, batch_size: int) -> tuple[np.ndarray, np.ndarray]:
        """(float32 images in [0,1], one-hot or int64 labels) — the
        reference tutorial API (``MNISTDist.py:178``)."""
        idx = self._next_indices(batch_size)
        xs = self._gather(idx)
        if self.one_hot:
            ys = None
            if self._images_u8 is not None:
                from distributed_tensorflow_tpu import native

                ys = native.onehot_gather(self.labels_int, idx, self.num_classes)
            if ys is None:
                ys = np.zeros((batch_size, self.num_classes), np.float32)
                ys[np.arange(batch_size), self.labels_int[idx]] = 1.0
        else:
            ys = self.labels_int[idx]
        return xs, ys

    def next_batch_raw(self, batch_size: int) -> tuple[np.ndarray, np.ndarray]:
        """(uint8 images, int32 class ids) — the thin-wire batch format.

        Host->device traffic per example drops from 3176 B (f32 pixels +
        one-hot f32) to 788 B; models normalize on device (uint8 inputs are
        recognized in ``apply``) and the loss/accuracy ops accept integer
        labels. On tunneled or PCIe-attached accelerators the input link is
        the throughput ceiling, so this is the fast path ``bench.py`` and
        ``--raw_input`` use. Same shuffled-epoch index stream as
        ``next_batch``.
        """
        idx = self._next_indices(batch_size)
        return self._raw_u8()[idx], self.labels_int[idx].astype(np.int32)

    def _raw_u8(self) -> np.ndarray:
        if self._images_u8 is not None:
            return self._images_u8  # native u8 source: exact bytes
        if getattr(self, "_u8_cache", None) is None:
            # one-time quantization of float-stored sources (synthetic /
            # CIFAR pickles); kept separate from _images_u8 so the f32
            # next_batch path stays exactly as loaded
            self._u8_cache = np.clip(
                np.round(self._images_f32 * 255.0), 0, 255
            ).astype(np.uint8).reshape(len(self._images_f32), -1)
        return self._u8_cache

    def _gather(self, idx: np.ndarray) -> np.ndarray:
        if self._images_u8 is not None:
            from distributed_tensorflow_tpu import native

            out = native.gather_normalize(self._images_u8, idx)
            if out is not None:
                return out
            return self._images_u8[idx].astype(np.float32) / 255.0
        return self._images_f32[idx]

    def shard(self, index: int, count: int) -> "DataSet":
        """Disjoint contiguous shard — the sync-DP alternative to the
        reference's everyone-loads-everything scheme."""
        sl = slice(index * self.num_examples // count,
                   (index + 1) * self.num_examples // count)
        src = self._images_u8 if self._images_u8 is not None else self._images_f32
        return DataSet(src[sl], self.labels_int[sl], one_hot=self.one_hot,
                       num_classes=self.num_classes, seed=index)


@dataclass
class Datasets:
    train: DataSet
    test: DataSet
    validation: DataSet | None = None
    source: str = "synthetic"  # "idx" | "cifar" | "synthetic"
    meta: dict = field(default_factory=dict)


def _load_mnist_idx(data_dir: str) -> dict[str, np.ndarray] | None:
    paths = {k: find_idx_file(data_dir, v) for k, v in _MNIST_FILES.items()}
    if not all(paths.values()):
        return None

    def _read(p: str) -> np.ndarray:
        from distributed_tensorflow_tpu import native

        arr = native.read_idx_u8(p)  # fast path: uncompressed u8 via C++
        return arr if arr is not None else read_idx(p)

    return {k: _read(p) for k, p in paths.items()}


def _load_cifar10(data_dir: str):
    """CIFAR-10 python-version pickle batches (data_batch_1..5, test_batch)."""
    def _find(name):
        for root in (data_dir, os.path.join(data_dir, "cifar-10-batches-py")):
            p = os.path.join(root, name)
            if os.path.exists(p):
                return p
        return None

    train_paths = [_find(f"data_batch_{i}") for i in range(1, 6)]
    test_path = _find("test_batch")
    if not all(train_paths) or test_path is None:
        return None

    def _read(p):
        with open(p, "rb") as f:
            d = pickle.load(f, encoding="bytes")
        x = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return x.astype(np.float32) / 255.0, np.asarray(d[b"labels"], np.int64)

    xs, ys = zip(*[_read(p) for p in train_paths])
    tx, ty = _read(test_path)
    return np.concatenate(xs), np.concatenate(ys), tx, ty


def read_data_sets(
    data_dir: str,
    one_hot: bool = True,
    dataset: str = "mnist",
    seed: int = 0,
    validation_size: int = 0,
    seq_len: int = 256,
    vocab_size: int = 64,
) -> Datasets:
    """API parity with the tutorial loader the reference imports
    (``MNISTDist.py:11,167``), extended with ``dataset`` selection:
    "mnist" | "fashion_mnist" (same IDX format) | "cifar10" | "lm"
    (procedural associative-recall token sequences for the causal-LM
    family; ``seq_len``/``vocab_size`` apply only there).
    Falls back to procedural data when files are absent (offline envs)."""
    dataset = dataset.lower().replace("-", "_")
    if dataset == "lm":
        from distributed_tensorflow_tpu.data.lm import LMDataSet

        train = LMDataSet(LM_TRAIN, seq_len, vocab_size, seed=seed)
        test = LMDataSet(LM_TEST, seq_len, vocab_size, seed=seed + 10_000)
        val = None
        if validation_size:
            # generated independently (own seed space), not carved from a
            # finite split — any positive size works
            if validation_size < 0:
                raise ValueError(
                    f"validation_size={validation_size} must be >= 0")
            val = LMDataSet(validation_size, seq_len, vocab_size,
                            seed=seed + 20_000)
        return Datasets(
            train=train, test=test, validation=val, source="synthetic",
            meta={"kind": "lm", "seq_len": seq_len,
                  "vocab_size": vocab_size,
                  "num_classes": vocab_size},
        )
    if dataset in ("mnist", "fashion_mnist"):
        raw = _load_mnist_idx(data_dir) if data_dir and os.path.isdir(data_dir) else None
        if raw is not None:
            # keep u8 storage: batches normalize on demand (native gather)
            trx = raw["train_images"].reshape(-1, 784)
            trl = raw["train_labels"].astype(np.int64)
            tex = raw["test_images"].reshape(-1, 784)
            tel = raw["test_labels"].astype(np.int64)
            source = "idx"
        else:
            trx, trl = synthetic.synthetic_digits(SYNTHETIC_TRAIN, seed=seed)
            tex, tel = synthetic.synthetic_digits(SYNTHETIC_TEST, seed=seed + 1)
            source = "synthetic"
        meta = {"image_size": 28, "channels": 1, "num_classes": 10, "flat": True}
    elif dataset == "cifar10":
        raw = _load_cifar10(data_dir) if data_dir and os.path.isdir(data_dir) else None
        if raw is not None:
            trx, trl, tex, tel = raw
            source = "cifar"
        else:
            trx, trl = synthetic.synthetic_cifar(SYNTHETIC_TRAIN, seed=seed)
            tex, tel = synthetic.synthetic_cifar(SYNTHETIC_TEST, seed=seed + 1)
            source = "synthetic"
        meta = {"image_size": 32, "channels": 3, "num_classes": 10, "flat": False}
    else:
        raise ValueError(f"unknown dataset {dataset!r}")

    val = None
    if validation_size:
        if not 0 <= validation_size < len(trx):
            raise ValueError(
                f"validation_size={validation_size} must be in "
                f"[0, {len(trx)}) for this train split"
            )
        val = DataSet(trx[:validation_size], trl[:validation_size],
                      one_hot=one_hot, seed=seed + 2)
        trx, trl = trx[validation_size:], trl[validation_size:]

    return Datasets(
        train=DataSet(trx, trl, one_hot=one_hot, seed=seed),
        test=DataSet(tex, tel, one_hot=one_hot, seed=seed + 1),
        validation=val,
        source=source,
        meta=meta,
    )
