"""ResNet-20: shapes, param count, BN state semantics, train convergence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.models import ResNet20, get_model
from distributed_tensorflow_tpu.ops import nn
from distributed_tensorflow_tpu.parallel import make_dp_train_step, make_mesh, shard_batch
from distributed_tensorflow_tpu.parallel.data_parallel import replicate_state
from distributed_tensorflow_tpu.training import adam, create_train_state, make_train_step
from distributed_tensorflow_tpu.training.train_state import evaluate


@pytest.fixture(scope="module")
def model():
    return ResNet20()


@pytest.fixture(scope="module")
def variables(model):
    return model.init(jax.random.PRNGKey(0))


def test_registry_names():
    assert isinstance(get_model("resnet20"), ResNet20)
    assert get_model("resnet32").n == 5


def test_param_count(model, variables):
    # classic CIFAR ResNet-20 is ~0.27M params
    n = model.num_params(variables)
    assert 260_000 < n < 290_000, n


def test_forward_shapes(model, variables):
    x = jnp.ones((4, 32, 32, 3))
    logits = model.apply(variables, x)
    assert logits.shape == (4, 10)


def test_train_mode_returns_new_state(model, variables):
    x = jax.random.normal(jax.random.key(0), (4, 32, 32, 3))
    logits, new_state = model.apply(variables, x, train=True)
    assert logits.shape == (4, 10)
    # running stats moved away from init
    m0 = np.asarray(variables["state"]["stem"]["bn"]["mean"])
    m1 = np.asarray(new_state["stem"]["bn"]["mean"])
    assert not np.allclose(m0, m1)


def test_batch_norm_train_normalizes():
    x = jax.random.normal(jax.random.key(1), (16, 8, 8, 4)) * 3 + 5
    y, (nm, nv) = nn.batch_norm(
        x, jnp.ones(4), jnp.zeros(4), jnp.zeros(4), jnp.ones(4), train=True
    )
    np.testing.assert_allclose(np.asarray(y.mean(axis=(0, 1, 2))), 0.0, atol=1e-4)
    np.testing.assert_allclose(np.asarray(y.std(axis=(0, 1, 2))), 1.0, atol=1e-2)
    # EMA moved toward batch stats
    assert np.all(np.asarray(nm) > 0)


def test_batch_norm_eval_uses_running_stats():
    x = jnp.full((2, 2, 2, 1), 7.0)
    y, (nm, nv) = nn.batch_norm(
        x, jnp.ones(1), jnp.zeros(1), jnp.full(1, 7.0), jnp.ones(1), train=False
    )
    np.testing.assert_allclose(np.asarray(y), 0.0, atol=1e-3)
    np.testing.assert_allclose(np.asarray(nm), 7.0)


def test_resnet_train_step_updates_state(model):
    opt = adam(1e-3)
    state = create_train_state(model, opt, seed=0)
    assert state.model_state  # non-empty collection
    step_fn = make_train_step(model, opt, donate=False)
    x = jax.random.normal(jax.random.key(2), (8, 32, 32, 3))
    y = jax.nn.one_hot(jnp.arange(8) % 10, 10)
    new, metrics = step_fn(state, (x, y))
    assert int(new.step) == 1
    s0 = np.asarray(state.model_state["stem"]["bn"]["mean"])
    s1 = np.asarray(new.model_state["stem"]["bn"]["mean"])
    assert not np.allclose(s0, s1)


def test_resnet_convergence_synthetic_cifar():
    from distributed_tensorflow_tpu.data import read_data_sets

    ds = read_data_sets("/nonexistent", one_hot=True, dataset="cifar10")
    model = ResNet20()
    opt = adam(1e-3)
    state = create_train_state(model, opt, seed=0)
    step_fn = make_train_step(model, opt)
    first = None
    for i in range(60):
        batch = ds.train.next_batch(32)
        state, m = step_fn(state, batch)
        if first is None:
            first = float(m["loss"])
    assert float(m["loss"]) < first * 0.8
    res = evaluate(model, state.params, ds.test, batch_size=500,
                   model_state=state.model_state)
    assert res["accuracy"] > 0.35


def test_resnet_dp_step(model):
    mesh = make_mesh()
    opt = adam(1e-3)
    state = replicate_state(mesh, create_train_state(model, opt, seed=0))
    step_fn = make_dp_train_step(model, opt, mesh, donate=False)
    x = jax.random.normal(jax.random.key(3), (16, 32, 32, 3))
    y = jax.nn.one_hot(jnp.arange(16) % 10, 10)
    state, metrics = step_fn(state, shard_batch(mesh, (x, y)))
    assert np.isfinite(float(metrics["loss"]))
    # BN state replicated identically across devices
    mean = state.model_state["stem"]["bn"]["mean"]
    shards = [np.asarray(s.data) for s in mean.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)
