"""Aggregate per-op device time from a jax.profiler trace.

The only reliable per-op instrument on tunneled chips (PERF.md): the
trace's device "XLA Ops" lane durations sum to the wall, per-op, where
RPC-latency-polluted microbenchmarks are ~10x wrong. Loads the newest
``*.trace.json.gz`` under a profile dir, selects the XLA Ops thread,
and prints a table: op name, calls, total ms, share, bytes accessed.

Usage: python tools/trace_ops.py /tmp/profile-dir [top_n]
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import sys


def load_trace(profile_dir: str) -> dict:
    paths = sorted(
        glob.glob(os.path.join(profile_dir, "**", "*.trace.json.gz"),
                  recursive=True),
        key=os.path.getmtime)
    if not paths:
        raise FileNotFoundError(f"no *.trace.json.gz under {profile_dir}")
    with gzip.open(paths[-1], "rt") as f:
        return json.load(f)


def xla_op_events(trace: dict) -> list[dict]:
    """Complete events on any thread named 'XLA Ops' (the device lane)."""
    tid_names: dict[tuple, str] = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tid_names[(e.get("pid"), e.get("tid"))] = (
                e.get("args", {}).get("name", ""))
    out = []
    for e in trace.get("traceEvents", []):
        if e.get("ph") == "X" and "dur" in e:
            if "XLA Ops" in tid_names.get((e.get("pid"), e.get("tid")), ""):
                out.append(e)
    return out


def aggregate(events: list[dict]) -> list[dict]:
    agg: dict[str, dict] = collections.defaultdict(
        lambda: {"calls": 0, "us": 0.0, "bytes": 0})
    for e in events:
        name = e.get("name", "?")
        a = agg[name]
        a["calls"] += 1
        a["us"] += float(e["dur"])
        args = e.get("args", {})
        try:
            a["bytes"] += int(args.get("bytes_accessed", 0))
        except (TypeError, ValueError):
            pass
    rows = [{"op": k, **v} for k, v in agg.items()]
    rows.sort(key=lambda r: -r["us"])
    return rows


def main(profile_dir: str, top_n: int = 25) -> None:
    rows = aggregate(xla_op_events(load_trace(profile_dir)))
    total_us = sum(r["us"] for r in rows)
    print(f"total device op time: {total_us / 1e3:.2f} ms "
          f"across {sum(r['calls'] for r in rows)} op executions")
    print(f"{'op':<52} {'calls':>6} {'ms':>9} {'share':>6} {'GB':>8}")
    for r in rows[:top_n]:
        print(f"{r['op'][:52]:<52} {r['calls']:>6} {r['us'] / 1e3:>9.2f} "
              f"{r['us'] / total_us:>6.1%} {r['bytes'] / 2**30:>8.2f}")
    rest = rows[top_n:]
    if rest:
        us = sum(r["us"] for r in rest)
        print(f"{'(other ' + str(len(rest)) + ' ops)':<52} "
              f"{sum(r['calls'] for r in rest):>6} {us / 1e3:>9.2f} "
              f"{us / total_us:>6.1%}")


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]) if len(sys.argv) > 2 else 25)
