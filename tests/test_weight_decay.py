"""Decoupled weight decay: exact update math per optimizer, zero-decay
parity with the previous behavior, and the ps-mode rejection."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.training import get_optimizer, sgd
from distributed_tensorflow_tpu.training.train_state import adam, momentum


def _p():
    return {"w": jnp.array([1.0, -2.0])}


def _g():
    return {"w": jnp.array([0.5, 0.5])}


def test_sgd_decay_math():
    opt = sgd(0.1, weight_decay=0.01)
    updates, _ = opt.update(_g(), opt.init(_p()), _p())
    # -lr*(g + wd*p)
    expected = -0.1 * (np.array([0.5, 0.5]) + 0.01 * np.array([1.0, -2.0]))
    np.testing.assert_allclose(np.asarray(updates["w"]), expected, rtol=1e-6)


def test_momentum_decay_is_decoupled():
    """Decay applies to the update directly — it must NOT enter the
    velocity (where beta would compound it)."""
    opt = momentum(0.1, beta=0.9, weight_decay=0.01)
    st = opt.init(_p())
    updates, st = opt.update(_g(), st, _p())
    # velocity holds only the gradient
    np.testing.assert_allclose(np.asarray(st["w"]), [0.5, 0.5], rtol=1e-6)
    expected = -0.1 * (np.array([0.5, 0.5]) + 0.01 * np.array([1.0, -2.0]))
    np.testing.assert_allclose(np.asarray(updates["w"]), expected, rtol=1e-6)


def test_adamw_decay_outside_moments():
    wd = 0.01
    plain = adam(1e-3)
    decayed = adam(1e-3, weight_decay=wd)
    u0, _ = plain.update(_g(), plain.init(_p()), _p())
    u1, _ = decayed.update(_g(), decayed.init(_p()), _p())
    # difference is exactly -lr*wd*p (decay never touches m/v)
    diff = np.asarray(u1["w"]) - np.asarray(u0["w"])
    np.testing.assert_allclose(diff, -1e-3 * wd * np.array([1.0, -2.0]),
                               rtol=1e-5)


@pytest.mark.parametrize("name", ["sgd", "momentum", "adam"])
def test_zero_decay_is_previous_behavior(name):
    plain = get_optimizer(name, 0.05)
    explicit = get_optimizer(name, 0.05, weight_decay=0.0)
    u0, _ = plain.update(_g(), plain.init(_p()), _p())
    u1, _ = explicit.update(_g(), explicit.init(_p()), _p())
    np.testing.assert_array_equal(np.asarray(u0["w"]), np.asarray(u1["w"]))


def test_decay_shrinks_weights_in_training():
    """End-to-end: with zero gradients (constant loss can't be arranged
    easily, so use huge decay vs none on the same run), the decayed run's
    weight norm must end smaller."""
    from distributed_tensorflow_tpu.models import MLP
    from distributed_tensorflow_tpu.training import create_train_state, make_train_step

    model = MLP(hidden_units=32)
    x = jax.random.normal(jax.random.key(0), (16, 784))
    y = jax.nn.one_hot(jnp.arange(16) % 10, 10)

    norms = {}
    for wd in (0.0, 0.3):
        opt = get_optimizer("sgd", 0.05, weight_decay=wd)
        state = create_train_state(model, opt, seed=0)
        step = make_train_step(model, opt, keep_prob=1.0, donate=False)
        for _ in range(20):
            state, _ = step(state, (x, y))
        norms[wd] = float(sum(jnp.sum(jnp.square(p))
                              for p in jax.tree.leaves(state.params)))
    assert norms[0.3] < norms[0.0] * 0.8


def test_ps_mode_rejects_weight_decay():
    from distributed_tensorflow_tpu.parallel.ps_emulation import run_worker

    class F:
        lr_schedule = "constant"
        warmup_steps = 0
        accum_steps = 1
        weight_decay = 0.01

    with pytest.raises(ValueError, match="weight_decay is not supported"):
        run_worker(None, F)


def test_negative_decay_rejected():
    with pytest.raises(ValueError, match="must be >= 0"):
        sgd(0.1, weight_decay=-0.01)
    with pytest.raises(ValueError, match="must be >= 0"):
        get_optimizer("adam", 1e-3, weight_decay=-1.0)
