"""The router's client-side replica model (r22): one object per engine
replica holding the folded health picture, the circuit-breaker state
machine, and the in-flight ledger the power-of-two-choices dispatcher
weighs.

State machine (``ReplicaState``):

- **healthy** — dispatchable. The steady state.
- **draining** — the replica answered its /healthz poll with 503 (HBM
  floor, SLO fast-burn, closed batcher, KV-page floor — the replica's
  own drain signals). No NEW dispatch; in-flight requests complete; the
  next 200 poll flips it back. Drain is reversible and poll-driven —
  the replica asked to be left alone, it did not disappear.
- **ejected** — the circuit breaker tripped: ``breaker_fails``
  consecutive dispatch/poll failures (connect-fail or 5xx result).
  After ``eject_s`` (doubling per consecutive re-ejection, capped) the
  replica becomes a HALF-OPEN probe target: exactly one trial request
  may flow; success closes the breaker, failure re-ejects with a longer
  cooldown. An unreachable replica therefore costs the fleet one probe
  per cooldown, not a retry storm.

Admin drain (``set_admin_drain``) is an orthogonal bit the rolling-
reload orchestration sets: an admin-drained replica takes no new
dispatch whatever its health state, so a checkpoint swap happens on a
quiet engine.

Locking: ``Replica._lock`` is a LEAF lock — every mutable field lives
under it, no I/O and no other lock is ever acquired while holding it,
and state-transition span emission happens from the returned transition
tag AFTER release. Transports are stateless and lock-free.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np


class TransportError(Exception):
    """The replica could not be reached at all (connect refused, socket
    reset, DNS): the retriable failure class, distinct from an HTTP
    status the replica itself chose to send."""


class HttpTransport:
    """Stateless stdlib HTTP client for one replica base URL."""

    def __init__(self, base_url: str, timeout_s: float = 10.0):
        self.base_url = base_url.rstrip("/")
        if "://" not in self.base_url:
            self.base_url = "http://" + self.base_url
        self.timeout_s = float(timeout_s)

    def _round_trip(self, req) -> tuple[int, dict]:
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return r.status, json.loads(r.read().decode() or "{}")
        except urllib.error.HTTPError as e:
            # a status the replica chose (429/503/500...): report it
            try:
                body = json.loads(e.read().decode() or "{}")
            except (ValueError, OSError):
                body = {}
            return e.code, body
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise TransportError(f"{self.base_url}: {e}") from e

    def get(self, path: str) -> tuple[int, dict]:
        return self._round_trip(urllib.request.Request(
            self.base_url + path, method="GET"))

    def post(self, path: str, obj: dict) -> tuple[int, dict]:
        body = json.dumps(obj).encode()
        return self._round_trip(urllib.request.Request(
            self.base_url + path, data=body, method="POST",
            headers={"Content-Type": "application/json"}))

    def __repr__(self):
        return f"HttpTransport({self.base_url})"


class LocalTransport:
    """In-process transport over an ``InferenceServer`` that was never
    started: the same (status, body) surface ``_Handler`` puts on the
    wire, without sockets — what bench's host-only ``router_phase`` and
    the fast-tier tests dispatch through."""

    def __init__(self, server):
        self.server = server

    def get(self, path: str) -> tuple[int, dict]:
        srv = self.server
        if path == "/healthz":
            health = srv.healthz()
            return (200 if health["ok"] else 503), health
        if path == "/metrics":
            return 200, srv.metrics()
        if path == "/stats":
            return 200, srv.stats()
        return 404, {"error": f"no route {path}"}

    def post(self, path: str, obj: dict) -> tuple[int, dict]:
        from distributed_tensorflow_tpu.serving.batcher import (
            RejectedError,
        )

        srv = self.server
        rid = obj.get("request_id")
        try:
            if path == "/v1/predict":
                out, meta = srv.client.predict_ex(
                    np.asarray(obj["inputs"]),
                    timeout_ms=obj.get("timeout_ms"), request_id=rid)
                return 200, {"outputs": np.asarray(out).tolist(), **meta}
            if path == "/v1/generate":
                toks, meta = srv.client.generate_ex(
                    obj["prompt"],
                    max_new_tokens=obj.get("max_new_tokens"),
                    temperature=obj.get("temperature"),
                    seed=obj.get("seed"),
                    timeout_ms=obj.get("timeout_ms"), request_id=rid)
                return 200, {"tokens": np.asarray(toks).tolist(), **meta}
            if path == "/admin/reload":
                report = srv.engine.reload_if_newer()
                return 200, {"reloaded": report is not None,
                             "report": report,
                             "params_step": srv.engine.step}
            return 404, {"error": f"no route {path}"}
        except RejectedError as e:
            return 429, {"error": e.reason, "rejected": True,
                         "request_id": getattr(e, "request_id", None)
                         or rid}
        except (KeyError, ValueError) as e:
            return 400, {"error": f"{type(e).__name__}: {e}",
                         "request_id": rid}
        except TimeoutError as e:
            return 504, {"error": "request timed out in flight",
                         "request_id": getattr(e, "request_id", None)
                         or rid}
        except Exception as e:  # noqa: BLE001 — mirror the wire handler
            return 500, {"error": f"{type(e).__name__}: {e}",
                         "request_id": rid}

    def __repr__(self):
        return f"LocalTransport({self.server.address})"


class ReplicaState:
    HEALTHY = "healthy"
    DRAINING = "draining"
    EJECTED = "ejected"


EJECT_BACKOFF_CAP = 8  # max cooldown multiplier: eject_s * 2**(n-1) <= *8


class Replica:
    """One replica's router-side ledger. All mutation under the leaf
    ``_lock``; methods that change state return a transition tag (or
    None) so the caller emits spans/flight records OUTSIDE the lock."""

    def __init__(self, name: str, transport, *,
                 breaker_fails: int = 3, eject_s: float = 1.0):
        self.name = name
        self.transport = transport
        self.breaker_fails = max(int(breaker_fails), 1)
        self.eject_s = float(eject_s)
        self._lock = threading.Lock()
        self.state = ReplicaState.HEALTHY
        self.health: dict = {}     # last /healthz body
        self.signals: dict = {}    # folded /metrics signals
        self.inflight = 0
        self.consecutive_failures = 0
        self.ejected_until = 0.0
        self._eject_streak = 0     # consecutive ejections -> backoff
        self.probe_inflight = False
        self.admin_drain = False
        self.last_served_step = None
        self.dispatches = 0
        self.failures = 0
        self.ejections = 0

    # ------------------------------------------------------ health fold

    def observe_health(self, status: int | None, body: dict | None,
                       now: float, *, metrics: dict | None = None,
                       error: str | None = None) -> str | None:
        """Fold one poll result. ``status=None`` + ``error`` means the
        poll itself failed to connect — breaker-feeding evidence, same
        as a dispatch connect-fail."""
        with self._lock:
            if metrics is not None:
                hbm = metrics.get("hbm") or {}
                self.signals = {
                    "params_step": metrics.get("params_step"),
                    "goodput_uptime_pct": metrics.get(
                        "goodput_uptime_pct"),
                    "hbm_headroom_pct": hbm.get("headroom_pct"),
                    "kv_pages": hbm.get("kv_pages"),
                    "slo": metrics.get("slo"),
                    "p99_trend": {
                        route: (metrics.get(route) or {}).get(
                            "health", {}).get("p99_trend")
                        for route in ("predict", "generate")
                        if route in metrics},
                }
            if status is None:
                self.health = {"ok": False, "error": error}
                return self._note_failure_locked(now)
            self.health = dict(body or {})
            if status == 200 and body and body.get("ok"):
                self.consecutive_failures = 0
                if self.state == ReplicaState.DRAINING:
                    self.state = ReplicaState.HEALTHY
                    return "undrain"
                if self.state == ReplicaState.HEALTHY:
                    self._eject_streak = 0
                # ejected replicas heal through the half-open dispatch
                # probe, not the poll: a 200 /healthz proves the socket,
                # the probe proves the serving path
                return None
            # 503 (or malformed body): the replica asked to drain
            if self.state == ReplicaState.HEALTHY:
                self.state = ReplicaState.DRAINING
                return "drain"
            return None

    # -------------------------------------------------- breaker surface

    def _note_failure_locked(self, now: float) -> str | None:
        self.consecutive_failures += 1
        self.failures += 1
        if self.state == ReplicaState.EJECTED:
            # a failed half-open probe: re-eject with a longer cooldown
            if now >= self.ejected_until:
                return self._eject_locked(now)
            return None
        if self.consecutive_failures >= self.breaker_fails:
            return self._eject_locked(now)
        return None

    def _eject_locked(self, now: float) -> str:
        self.state = ReplicaState.EJECTED
        self._eject_streak += 1
        mult = min(2 ** (self._eject_streak - 1), EJECT_BACKOFF_CAP)
        self.ejected_until = now + self.eject_s * mult
        self.probe_inflight = False
        self.ejections += 1
        return "eject"

    def note_failure(self, now: float) -> str | None:
        """A dispatch attempt failed (connect-fail or 5xx)."""
        with self._lock:
            return self._note_failure_locked(now)

    def note_success(self) -> str | None:
        """A dispatch attempt succeeded (any status the replica chose
        below 500 — a 429 replica is alive and judging)."""
        with self._lock:
            self.consecutive_failures = 0
            if self.state == ReplicaState.EJECTED:
                # the half-open probe came back: close the breaker
                self.state = ReplicaState.HEALTHY
                self._eject_streak = 0
                return "heal"
            return None

    # ------------------------------------------------- dispatch surface

    def dispatchable(self, now: float) -> bool:
        with self._lock:
            return self._dispatchable_locked(now)

    def _dispatchable_locked(self, now: float) -> bool:
        if self.admin_drain:
            return False
        if self.state == ReplicaState.HEALTHY:
            return True
        if self.state == ReplicaState.EJECTED:
            # half-open trickle: one probe past the cooldown
            return now >= self.ejected_until and not self.probe_inflight
        return False  # draining

    def begin_dispatch(self, now: float) -> bool:
        """Claim a dispatch slot (and, half-open, THE probe slot).
        False when the replica stopped being dispatchable since it was
        picked — the dispatcher just picks again."""
        with self._lock:
            if not self._dispatchable_locked(now):
                return False
            if self.state == ReplicaState.EJECTED:
                self.probe_inflight = True
            self.inflight += 1
            self.dispatches += 1
            return True

    def end_dispatch(self, ok: bool, now: float,
                     served_step=None) -> str | None:
        with self._lock:
            self.inflight = max(self.inflight - 1, 0)
            self.probe_inflight = False
            if served_step is not None:
                self.last_served_step = served_step
        return self.note_success() if ok else self.note_failure(now)

    def load(self) -> float:
        """The p2c weight: requests the router has in flight here plus
        the replica's own last-polled queue depth."""
        with self._lock:
            depth = self.health.get("queue_depth") or 0
            return self.inflight + float(depth)

    def set_admin_drain(self, on: bool) -> None:
        with self._lock:
            self.admin_drain = bool(on)

    def state_name(self) -> str:
        with self._lock:
            return self.state

    def is_healthy(self) -> bool:
        """Healthy AND serving (not admin-drained) — the router's
        min-healthy accounting unit."""
        with self._lock:
            return (self.state == ReplicaState.HEALTHY
                    and not self.admin_drain)

    def inflight_count(self) -> int:
        with self._lock:
            return self.inflight

    def snapshot(self, now: float | None = None) -> dict:
        now = time.monotonic() if now is None else now
        with self._lock:
            return {
                "name": self.name,
                "state": self.state,
                "admin_drain": self.admin_drain,
                "dispatchable": self._dispatchable_locked(now),
                "inflight": self.inflight,
                "queue_depth": self.health.get("queue_depth"),
                "params_step": self.health.get("params_step",
                                               self.signals.get(
                                                   "params_step")),
                "last_served_step": self.last_served_step,
                "consecutive_failures": self.consecutive_failures,
                "dispatches": self.dispatches,
                "failures": self.failures,
                "ejections": self.ejections,
                "eject_cooldown_s": (
                    round(max(self.ejected_until - now, 0.0), 3)
                    if self.state == ReplicaState.EJECTED else 0.0),
                "slo_fast_burn": self.health.get("slo_fast_burn"),
                "hbm_headroom_pct": self.health.get("hbm_headroom_pct"),
                "goodput_uptime_pct": self.signals.get(
                    "goodput_uptime_pct"),
                "p99_trend": self.signals.get("p99_trend"),
            }
