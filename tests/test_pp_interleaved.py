"""Interleaved virtual-stage pipeline schedule (--virtual_stages:
parallel/pp_schedule.py + the schedule-table tick loop in
parallel/pipeline_parallel.py). Pins:

- the schedule table's structural invariants (bijection, one-tick
  dataflow dependency, GPipe as the exact V=1 special case) and the
  masked-FLOP cost model (scheduled block computations strictly DROP
  vs the GPipe baseline — the whole point of the change);
- EXACT trajectories: V=2 training bit-matches V=1 on the 8-device
  mesh, --clip_norm set and dropout on (same PRNG folds, same
  masked-mean loss, canonical-order clip norm) — host-fed and
  device-resident chunked steps both;
- checkpoint layout-independence: save under V=2 -> restore under V=1
  (and the reverse) continues the bit-exact trajectory, and mid-chunk
  resume under --pipeline --device_data --virtual_stages=2 matches the
  uninterrupted run bit-for-bit;
- parse-time flag validation (the in-step ValueError moved to the
  command line)."""

import subprocess
import sys

import jax
import numpy as np
import pytest

from distributed_tensorflow_tpu.data.lm import LMDataSet
from distributed_tensorflow_tpu.models.transformer import TransformerLM
from distributed_tensorflow_tpu.parallel import MeshSpec, make_mesh
from distributed_tensorflow_tpu.parallel.pipeline_parallel import (
    fetch_state_pp,
    make_pp_train_step,
    pp_clip_transform,
    shard_state_pp,
    stack_block_params,
    stage_batch_pp,
    unstack_block_params,
)
from distributed_tensorflow_tpu.parallel.pp_schedule import (
    block_permutation,
    build_pp_schedule,
    validate_pp_layout,
)
from distributed_tensorflow_tpu.training import (
    create_train_state,
    get_optimizer,
    make_train_step,
)
from distributed_tensorflow_tpu.training.train_state import (
    clip_by_global_norm,
)

KW8 = dict(vocab_size=16, seq_len=32, d_model=32, num_heads=2,
           num_blocks=8)


# ------------------------------------------------------ schedule table


@pytest.mark.parametrize("k,m,v", [(2, 4, 1), (4, 4, 2), (2, 8, 2),
                                   (2, 6, 3), (4, 8, 2)])
def test_schedule_table_invariants(k, m, v):
    """Every (microbatch, virtual-stage) work unit runs exactly once on
    its device, consecutive virtual stages run exactly one tick apart
    on consecutive ring neighbors (so ONE carried activation slot
    suffices), and the tick count / useful fraction match the analytic
    formulas."""
    sched = build_pp_schedule(k, m, v)
    assert sched.num_ticks == m * v + k - 1
    assert sched.useful_tick_fraction == m * v / (m * v + k - 1)
    # tick of unit (microbatch mb, virtual stage j): on device j % k
    tick_of = {}
    for s in range(k):
        assert int(sched.valid[:, s].sum()) == m * v
        seen = set()
        for t in range(sched.num_ticks):
            if sched.valid[t, s]:
                unit = (int(sched.micro_index[t, s]),
                        int(sched.chunk_index[t, s]))
                assert unit not in seen  # each unit exactly once
                seen.add(unit)
                tick_of[(unit[0], unit[1] * k + s)] = t
        assert seen == {(mb, vg) for mb in range(m) for vg in range(v)}
    for mb in range(m):
        for j in range(v * k - 1):
            assert tick_of[(mb, j + 1)] == tick_of[(mb, j)] + 1


def test_gpipe_is_the_v1_special_case():
    sched = build_pp_schedule(4, 6, 1)
    assert sched.num_ticks == 6 + 4 - 1
    assert (sched.chunk_index == 0).all()
    for s in range(4):
        for t in range(sched.num_ticks):
            if sched.valid[t, s]:
                assert int(sched.micro_index[t, s]) == t - s


def test_scheduled_block_computations_strictly_drop():
    """The acceptance pin: at K=2, M=8, V=2 the per-step scheduled
    block executions (masked ticks included — they cost full FLOPs)
    strictly drop vs the GPipe baseline."""
    gpipe = build_pp_schedule(2, 8, 1).scheduled_block_computations(8)
    inter = build_pp_schedule(2, 8, 2).scheduled_block_computations(8)
    assert inter < gpipe
    assert gpipe == 9 * 2 * 4   # (M+K-1) ticks x K stages x L blocks
    assert inter == 17 * 2 * 2  # (MV+K-1) ticks x K stages x L/V blocks


def test_schedule_validation_errors():
    with pytest.raises(ValueError, match="rounds"):
        build_pp_schedule(2, 3, 2)  # M % K != 0 under interleaving
    with pytest.raises(ValueError, match="pipeline stages"):
        validate_pp_layout(6, 2, 2)  # 6 blocks can't form 4 groups
    with pytest.raises(ValueError, match=">= 1"):
        validate_pp_layout(8, 2, 0)


def test_block_permutation_and_stack_roundtrip():
    """Round-robin stacking: device s's positions hold virtual stages
    s, s+K, ... — and unstacking restores the standard list order
    exactly (the checkpoint-layout contract)."""
    perm = block_permutation(8, 2, 2)
    np.testing.assert_array_equal(perm, [0, 1, 4, 5, 2, 3, 6, 7])
    np.testing.assert_array_equal(block_permutation(8, 2, 1),
                                  np.arange(8))
    model = TransformerLM(**KW8)
    params = model.init(jax.random.PRNGKey(0))
    back = unstack_block_params(stack_block_params(params, perm), 8, perm)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------- exact-trajectory equality


def _run_pp(model, opt, base, mesh, batches, v, microbatches=4,
            keep_prob=0.5, clip=0.05):
    st = shard_state_pp(base, mesh, virtual_stages=v)
    step = make_pp_train_step(
        model, opt, mesh, microbatches=microbatches, keep_prob=keep_prob,
        donate=False,
        grad_transform=pp_clip_transform(clip, virtual_stages=v),
        virtual_stages=v)
    for b in batches:
        st, m = step(st, stage_batch_pp(mesh, b))
    return fetch_state_pp(st, model, k_stages=mesh.shape["model"],
                          virtual_stages=v), m


def test_v2_trajectory_bitmatches_v1_with_clip():
    """THE acceptance test: V=2 training bit-matches V=1 for a
    TransformerLM on the 8-device mesh (data=2, model=4), --clip_norm
    set and dropout ON — and both match the single-device clipped step
    to float tolerance. Same blocks applied to the same microbatches in
    the same order, canonical-order clip norm: nothing may wobble."""
    model = TransformerLM(**KW8)
    opt = get_optimizer("sgd", 0.05)
    base = create_train_state(model, opt, seed=0)
    mesh = make_mesh(MeshSpec(data=2, model=4))
    ds = LMDataSet(64, seq_len=32, vocab_size=16, seed=11)
    batches = [ds.next_batch(16) for _ in range(3)]

    host1, m1 = _run_pp(model, opt, base, mesh, batches, v=1)
    host2, m2 = _run_pp(model, opt, base, mesh, batches, v=2)
    assert float(m1["loss"]) == float(m2["loss"])
    assert float(m1["accuracy"]) == float(m2["accuracy"])
    for a, b in zip(jax.tree.leaves(host1.params),
                    jax.tree.leaves(host2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # and the pipeline still computes the single-device function
    # (keep_prob=1.0: the single step's dropout keys differ by design)
    b1, _ = _run_pp(model, opt, base, mesh, batches, v=1, keep_prob=1.0)
    b2, _ = _run_pp(model, opt, base, mesh, batches, v=2, keep_prob=1.0)
    single = create_train_state(model, opt, seed=0)
    step1 = make_train_step(model, opt, keep_prob=1.0, donate=False,
                            grad_transform=clip_by_global_norm(0.05))
    for b in batches:
        single, ms = step1(single, b)
    for got, _ in ((b1, 1), (b2, 2)):
        for a, c in zip(jax.tree.leaves(single.params),
                        jax.tree.leaves(got.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                       rtol=2e-4, atol=2e-5)


def test_v2_device_chunked_bitmatches_v1():
    """The device-resident chunked sampler under V=2 == V=1 bitwise:
    the DATA-axis-only sample fold is layout-independent, so the same
    rows are drawn and the schedule equivalence carries through the
    scan-chunked composition (clip on)."""
    from distributed_tensorflow_tpu.data.device_data import (
        put_device_data,
    )
    from distributed_tensorflow_tpu.training.device_step import (
        make_pp_device_train_step,
    )

    model = TransformerLM(**KW8)
    opt = get_optimizer("sgd", 0.05)
    base = create_train_state(model, opt, seed=0)
    mesh = make_mesh(MeshSpec(data=2, model=4))
    ds = LMDataSet(64, seq_len=32, vocab_size=16, seed=3)
    data = put_device_data(ds, mesh, data_sharded=True)
    outs = {}
    for v in (1, 2):
        dev = shard_state_pp(base, mesh, virtual_stages=v)
        dstep = make_pp_device_train_step(
            model, opt, mesh, 8, 4, keep_prob=1.0, chunk=2, donate=False,
            grad_transform=pp_clip_transform(0.05, virtual_stages=v),
            virtual_stages=v)
        dev, m = dstep(dev, data)
        outs[v] = (fetch_state_pp(dev, model, k_stages=4,
                                  virtual_stages=v), float(m["loss"]))
    assert outs[1][1] == outs[2][1]
    for a, b in zip(jax.tree.leaves(outs[1][0].params),
                    jax.tree.leaves(outs[2][0].params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------- checkpoint layout independence


def test_checkpoint_roundtrip_across_layouts(tmp_path):
    """Save under V=2 -> restore under V=1 (and the reverse) continues
    the exact trajectory: checkpoints are layout-independent because
    fetch_state_pp always emits the STANDARD block-list order."""
    from distributed_tensorflow_tpu.checkpoint import (
        restore_latest,
        save_checkpoint,
    )

    model = TransformerLM(**KW8)
    opt = get_optimizer("sgd", 0.05)
    base = create_train_state(model, opt, seed=3)
    mesh = make_mesh(MeshSpec(data=2, model=4))
    ds = LMDataSet(64, seq_len=32, vocab_size=16, seed=1)
    batches = [ds.next_batch(16) for _ in range(2)]

    # uninterrupted two-step reference (V=2 == V=1 by the test above)
    ref, _ = _run_pp(model, opt, base, mesh, batches, v=2,
                     keep_prob=1.0)

    for v_save, v_resume in ((2, 1), (1, 2)):
        mid, _ = _run_pp(model, opt, base, mesh, batches[:1], v=v_save,
                         keep_prob=1.0)
        d = tmp_path / f"ckpt_{v_save}to{v_resume}"
        save_checkpoint(str(d), mid, step=1)
        restored, step = restore_latest(
            str(d), create_train_state(model, opt, seed=9))
        assert step == 1
        done, _ = _run_pp(model, opt, restored, mesh, batches[1:],
                          v=v_resume, keep_prob=1.0)
        for a, b in zip(jax.tree.leaves(ref.params),
                        jax.tree.leaves(done.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _parse(flags, args):
    flags.FLAGS._reset()
    flags.FLAGS._parse(args)
    return flags.FLAGS


def test_device_pp_interleaved_mid_chunk_resume(tmp_path):
    """--pipeline --device_data --virtual_stages=2 through the
    production CLI: stop at a step that is NOT a chunk boundary, resume
    from the standard-layout checkpoint, and land on bit-identical
    params vs the uninterrupted run (the resumed loop realigns with a
    short chunk; state determinism must survive the different chunk
    partitioning and the stack/unstack round-trip)."""
    from distributed_tensorflow_tpu import flags
    from distributed_tensorflow_tpu.checkpoint import restore_latest
    from distributed_tensorflow_tpu.training.loop import train

    flags.define_reference_flags()

    def args_for(logdir, iters):
        return [f"--logdir={logdir}", f"--data_dir={tmp_path}/none",
                "--dataset=lm", "--model=lm", "--pipeline",
                "--model_axis=2", "--virtual_stages=2", "--num_blocks=4",
                "--d_model=32", "--num_heads=2", "--seq_len=32",
                "--vocab_size=16", "--batch_size=16",
                f"--training_iter={iters}", "--display_step=3",
                "--device_data", "--device_chunk=3", "--clip_norm=0.5",
                "--test_eval=false"]

    try:
        # interrupted: 5 steps (chunk lengths 3 + 2), then resume to 9
        res = train(_parse(flags, args_for(f"{tmp_path}/a", 5)),
                    mode="sync")
        assert res.final_step == 5
        res = train(_parse(flags, args_for(f"{tmp_path}/a", 9)),
                    mode="sync")
        assert res.final_step == 9
        # uninterrupted: straight to 9 (chunks 3 + 3 + 3)
        res_b = train(_parse(flags, args_for(f"{tmp_path}/b", 9)),
                      mode="sync")
        assert res_b.final_step == 9
    finally:
        flags.FLAGS._reset()

    model = TransformerLM(vocab_size=16, seq_len=32, d_model=32,
                          num_heads=2, num_blocks=4)
    opt = get_optimizer("sgd", 0.001)
    tmpl = lambda: create_train_state(model, opt, seed=9)
    got_a, step_a = restore_latest(f"{tmp_path}/a", tmpl())
    got_b, step_b = restore_latest(f"{tmp_path}/b", tmpl())
    assert step_a == step_b == 9
    for a, b in zip(jax.tree.leaves(got_a.params),
                    jax.tree.leaves(got_b.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------ parse-time validation


def test_virtual_stages_flag_validation():
    from distributed_tensorflow_tpu import flags

    flags.define_reference_flags()
    cases = [
        (["--virtual_stages=2"], "only applies to --pipeline"),
        (["--pipeline", "--model_axis=2", "--num_blocks=4",
          "--virtual_stages=4"], "block groups"),
        (["--pipeline", "--model_axis=2", "--num_blocks=4",
          "--batch_size=12", "--pp_microbatches=3",
          "--virtual_stages=2"], "rounds of the stage count"),
        (["--pipeline", "--batch_size=10", "--pp_microbatches=4"],
         "must split into"),
        (["--virtual_stages=0", "--pipeline"], "must be >= 1"),
    ]
    try:
        for args, want in cases:
            flags.FLAGS._reset()
            with pytest.raises(ValueError, match=want):
                flags.FLAGS._parse(args)
        # the valid interleaved config parses clean, V defaults to 1
        flags.FLAGS._reset()
        flags.FLAGS._parse(["--pipeline", "--model_axis=2",
                            "--num_blocks=8", "--virtual_stages=2",
                            "--batch_size=16"])
        assert flags.FLAGS.virtual_stages == 2
        flags.FLAGS._reset()
        flags.FLAGS._parse([])
        assert flags.FLAGS.virtual_stages == 1
    finally:
        flags.FLAGS._reset()


def test_fetch_state_pp_requires_k_for_interleaved():
    model = TransformerLM(**KW8)
    opt = get_optimizer("sgd", 0.05)
    mesh = make_mesh(MeshSpec(data=2, model=4))
    st = shard_state_pp(create_train_state(model, opt, seed=0), mesh,
                        virtual_stages=2)
    with pytest.raises(ValueError, match="k_stages"):
        fetch_state_pp(st, model, virtual_stages=2)


# ------------------------------------------------------- tooling


def test_trace_ops_schedule_mode(tmp_path):
    """tools/trace_ops.py --schedule prints the static tick table and
    the analytic useful-tick fraction without needing a chip or a
    trace file."""
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "trace_ops.py"),
         "--schedule", "2", "8", "2"],
        capture_output=True, text=True, timeout=300, cwd=root)
    assert p.returncode == 0, p.stderr
    assert "K=2 stages, M=8 microbatches, V=2" in p.stdout
    assert f"{16 / 17:.4f}" in p.stdout  # M*V/(M*V+K-1)
    assert "m7.v1" in p.stdout  # the last work unit appears in the table
