"""Resource plane: live HBM accounting with an OOM postmortem, a
recompilation sentry, and the per-mode collective-comm ledger.

PR 6/7 built the TIME plane — spans say where a step's milliseconds
went, MFU/goodput say what they bought, sentinels say whether the run
is dying. The RESOURCE plane was blind: ``device.memory_stats()`` was
read only inside bench.py, nothing counted XLA compiles after the
first, and only ``--zero`` carried analytic wire-bytes facts. The
three ways the runtime's invisibility kills a production run are
exactly these blind spots: silent HBM exhaustion, recompile storms,
and unaccounted collective traffic. This module is the third and
closing observability pillar — three coupled instruments over the one
telemetry spine:

- **HBM accounting** — ``MemoryMeter`` samples ``device.memory_stats()``
  at the EXISTING display/sync cadences (no new sync points; the CPU
  test mesh, which reports no stats, falls back to summing
  ``jax.live_arrays()`` bytes — a real live number, labeled
  ``source="live_arrays"``). Every loop variant and the serving stack
  emit ``hbm_in_use_bytes`` / ``hbm_peak_bytes`` / ``hbm_headroom_pct``
  next to ``images_per_sec``; each fresh sample also lands as an
  ``hbm_sample`` instant span (so it rides the span sink, the flight
  ring, and ``tools/fleet_report.py``'s per-host table). The live
  numbers cross-check against a STATIC analytic budget
  (``resource_budget`` — ``jax.eval_shape`` per-leaf params/opt plus an
  activation estimate, generalized beyond ``zero_memory_budget`` to the
  PP/TP/EP/SP layouts via each mode's own sharding rule).
- **OOM postmortem** — a chained ``sys.excepthook`` recognizes
  ``XlaRuntimeError`` / RESOURCE_EXHAUSTED and, before the normal
  telemetry dump, records the analytic budget table and the top-N
  largest live buffers (``jax.live_arrays()``) into the flight ring —
  so an OOM is diagnosable from ``flightrec-*.jsonl`` alone: the last
  memory samples (already riding the ring), what the budget SAID the
  state should cost, and which buffers actually held the HBM.
- **Recompilation sentry** — ``CompileSentry`` counts and times every
  XLA compile (a ``jax.monitoring`` backend-compile listener — cache
  hits don't fire) and keys dispatches by TRACED SIGNATURE
  (``observe(site, signature)``): the first signature per site is the
  expected first compile, every NEW signature after it is a recompile,
  and the report names the exact shape/dtype delta (the dimension that
  churned). ``--recompile_budget N`` arms a sentinel-ladder storm
  warning: more than N recompiles inside a rolling window prints the
  offending delta, drops a ``recompile_storm`` instant span, and dumps
  the flight recorder — the shape-churn failure mode the serving
  bucket system and schedules.py exist to prevent, now detectable when
  it regresses.
- **Comm ledger** — ``comm_ledger`` composes a static per-step analytic
  of collective wire bytes from the parallel modules' OWN row builders
  (``zero_comm_rows`` / ``pp_comm_rows`` / ``tp_comm_rows`` /
  ``ep_comm_rows`` / ``sp_comm_rows`` — the formulas live next to the
  collectives they price), surfaced as a ``comm_bytes_per_step`` scalar
  in every loop, a ``comm_ledger`` instant span (fleet_report's
  per-host column), and ``tools/trace_ops.py --comm``.

stdlib-only at import time (jax and the model/optimizer layers import
lazily inside the functions that need them) so the flags validator,
``tools/mem_report.py``, and bench's host-only phases can import this
from anywhere — the utils/telemetry contract.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import deque

from distributed_tensorflow_tpu.utils import telemetry

# error signatures that mean the device allocator gave up (the
# jaxlib XlaRuntimeError for RESOURCE_EXHAUSTED, and the strings the
# TPU/interpreter allocators put in the message)
OOM_SIGNS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
             "Allocation failure")
TOP_LIVE_BUFFERS = 8       # largest live buffers in the postmortem
MEM_SAMPLE_RING = 64       # samples MemoryMeter retains for dumps
RECOMPILE_WINDOW_S = 60.0  # rolling window behind --recompile_budget
MAX_SIGS_PER_SITE = 256    # signature-ledger cap (FIFO eviction)

F32_BYTES = 4


# --------------------------------------------------------- HBM metering


def _device_memory_sample() -> dict | None:
    """One live memory reading across the local devices.

    TPU/GPU backends report ``memory_stats()`` per device (bytes_in_use
    / peak_bytes_in_use / bytes_limit — summed here, per-device detail
    kept); the CPU test mesh reports None, so the fallback sums the
    bytes of every live jax array in the process — a real (if
    host-side) live-buffer number, labeled so nobody mistakes it for
    HBM. None only when there is no backend at all."""
    try:
        import jax

        per = []
        for d in jax.local_devices():
            try:
                ms = d.memory_stats()
            except Exception:  # noqa: BLE001 — absence of the stat
                ms = None
            if ms and "bytes_in_use" in ms:
                per.append({
                    "device": int(getattr(d, "id", len(per))),
                    "in_use": int(ms["bytes_in_use"]),
                    "peak": int(ms.get("peak_bytes_in_use",
                                       ms["bytes_in_use"])),
                    "limit": int(ms.get("bytes_limit", 0) or 0),
                })
        if per:
            return {"in_use": sum(p["in_use"] for p in per),
                    "peak": sum(p["peak"] for p in per),
                    "limit": sum(p["limit"] for p in per),
                    "source": "memory_stats", "per_device": per}
        total = sum(int(getattr(a, "nbytes", 0)) for a in jax.live_arrays())
        return {"in_use": total, "peak": total, "limit": 0,
                "source": "live_arrays", "per_device": []}
    except Exception:  # noqa: BLE001 — accounting never kills a run
        return None


def headroom_pct(in_use: int, limit: int) -> float:
    """Percent of the reported limit still free; -1.0 when the backend
    reports no limit (the CPU fallback) — 'unknown', never 'plenty'."""
    if limit and limit > 0:
        return round(100.0 * max(0.0, 1.0 - in_use / limit), 4)
    return -1.0


class MemoryMeter:
    """Live HBM accounting at the display cadence.

    ``scalars()`` is the loops' call: it re-samples every
    ``sample_every``-th call (``--hbm_sample_every`` display boundaries;
    the sample is a runtime stat query / live-array walk — no device
    sync) and returns the standard scalar family. Every FRESH sample
    also lands as an ``hbm_sample`` instant span, which puts it in the
    span sink (fleet_report's per-host hbm column), the flight ring
    (the OOM postmortem's recent-samples section), and nowhere near the
    hot path. ``peak`` is max(backend peak, own running max) so the CPU
    fallback still has a peak story. ``sample_fn`` is the test seam."""

    SCALARS = ("hbm_in_use_bytes", "hbm_peak_bytes", "hbm_headroom_pct")

    def __init__(self, analytic_bytes: int | None = None,
                 sample_every: int = 1, sample_fn=None):
        self.analytic_bytes = (int(analytic_bytes)
                               if analytic_bytes else None)
        self.sample_every = max(1, int(sample_every))
        self._sample_fn = sample_fn or _device_memory_sample
        self._samples: deque = deque(maxlen=MEM_SAMPLE_RING)
        self._lock = threading.Lock()
        self._peak = 0
        self._calls = 0
        self._last: dict | None = None

    def sample(self, tag: str = "") -> dict | None:
        """Take one fresh reading now; returns it (or None with no
        backend). Cheap: a per-device stats query, no sync."""
        s = self._sample_fn()
        if s is None:
            return None
        with self._lock:
            self._peak = max(self._peak, int(s.get("peak") or s["in_use"]))
            s = dict(s, peak=self._peak, t=time.time())
            self._samples.append(s)
            self._last = s
        telemetry.get_tracer().record_instant(
            "hbm_sample", in_use=int(s["in_use"]), peak=int(s["peak"]),
            limit=int(s.get("limit", 0)), source=s.get("source", "?"),
            **({"tag": tag} if tag else {}))
        return s

    def scalars(self) -> dict:
        """The display-cadence scalar family (re-sampling every
        ``sample_every``-th call). ``hbm_headroom_pct`` is -1.0 when the
        backend reports no limit (documented sentinel, not 'plenty')."""
        with self._lock:
            calls, self._calls = self._calls, self._calls + 1
            last = self._last
        if last is None or calls % self.sample_every == 0:
            last = self.sample() or last
        if last is None:
            return {}
        out = {
            "hbm_in_use_bytes": float(last["in_use"]),
            "hbm_peak_bytes": float(last["peak"]),
            "hbm_headroom_pct": headroom_pct(last["in_use"],
                                             last.get("limit", 0)),
        }
        if self.analytic_bytes:
            out["hbm_analytic_bytes"] = float(self.analytic_bytes)
        return out

    def sample_if_stale(self, max_age_s: float = 1.0,
                        tag: str = "") -> dict | None:
        """A fresh-enough reading without resampling on every call —
        the serving health poll's entry point (a hot /healthz must not
        turn into a sample-per-request span flood)."""
        with self._lock:
            last = self._last
        if last is not None and time.time() - last["t"] < max_age_s:
            return last
        return self.sample(tag=tag) or last

    def last_samples(self, k: int = MEM_SAMPLE_RING) -> list:
        with self._lock:
            return list(self._samples)[-k:]

    @property
    def last(self) -> dict | None:
        with self._lock:
            return self._last


# ------------------------------------------------------ analytic budget


def _abstract_state(model, optimizer):
    """(abstract params, abstract opt_state|None) via jax.eval_shape —
    no compute, no chip (the zero_memory_budget pattern)."""
    import jax

    if optimizer is not None:
        from distributed_tensorflow_tpu.training.train_state import (
            create_train_state,
        )

        st = jax.eval_shape(lambda: create_train_state(model, optimizer))
        return st.params, st.opt_state
    variables = jax.eval_shape(lambda: model.init(jax.random.PRNGKey(0)))
    if getattr(model, "stateful", False):
        variables = variables["params"]
    return variables, None


def _path_keys(path) -> tuple:
    """KeyPath -> tuple of dict keys / sequence indices — the ONE
    tree-path identity the divisor tables and the tp split-table
    lookups key by (the tuple sibling of ``utils.pytree.path_key``)."""
    return tuple(getattr(p, "key", getattr(p, "idx", None))
                 for p in path)


def _param_divisor_fn(mode: str, data_ways: int, model_axis: int,
                      zero_level: int, abstract_params):
    """(path, leaf) -> divisor: each mode's own sharding rule, spec-
    driven where a spec table exists (TP uses ``tp_param_specs``, EP the
    expert-leaf rule) rather than re-deriving layouts here."""
    import jax

    if mode == "zero3":
        return lambda path, leaf: data_ways
    if mode == "pp":
        # stage-sharded transformer blocks (num_blocks/K per device,
        # whatever V — interleaving permutes, it doesn't change the
        # per-device share); embed/head/norm replicate
        def div(path, leaf):
            return model_axis if "blocks" in _path_keys(path) else 1

        return div
    if mode == "tp":
        from jax.sharding import PartitionSpec as P

        from distributed_tensorflow_tpu.parallel.mesh import MODEL_AXIS
        from distributed_tensorflow_tpu.parallel.tensor_parallel import (
            tp_param_specs,
        )

        specs = tp_param_specs(abstract_params)
        flat = {_path_keys(path): spec
                for path, spec in jax.tree_util.tree_flatten_with_path(
                    specs, is_leaf=lambda x: isinstance(x, P))[0]}

        def div(path, leaf):
            spec = flat.get(_path_keys(path))
            return (model_axis if spec is not None
                    and any(ax == MODEL_AXIS for ax in spec) else 1)

        return div
    if mode == "ep":
        from distributed_tensorflow_tpu.parallel.expert_parallel import (
            _is_expert_leaf,
        )

        return lambda path, leaf: (model_axis if _is_expert_leaf(path)
                                   else 1)
    # dp / sp / local / zero1: params replicate
    return lambda path, leaf: 1


def _activation_rows(model, per_chip_batch: int,
                     seq_scale: int = 1) -> list[dict]:
    """Coarse per-chip activation estimate (f32 bytes of the layer
    outputs a training step keeps live) — the budget's third column.
    An ESTIMATE by design: remat/donation/XLA fusion all shrink the
    real number; the point is the order of magnitude next to the exact
    params/opt rows. ``seq_scale`` divides the token axis (SP)."""
    b = max(1, int(per_chip_batch))
    name = type(model).__name__
    rows = []

    def add(layer, elements):
        rows.append({"layer": layer, "bytes": int(elements) * F32_BYTES})

    if name == "DeepCNN":
        s = model.image_size
        s2 = -(-s // 2)
        add("conv1+pool", b * s * s * 32 + b * s2 * s2 * 32)
        add("conv2+pool", b * s2 * s2 * 64)
        add("fc", b * model.hidden_units)
        add("logits", b * model.num_classes)
    elif name == "MLP":
        add("hidden", b * model.hidden_units)
        add("logits", b * model.num_classes)
    elif name in ("ResNet", "ResNet20", "ResNet32"):
        size = model.image_size
        for si, width in enumerate(model.widths):
            if si > 0:
                size = -(-size // 2)
            add(f"stage{si}", model.n * 2 * b * size * size * width)
        add("head", b * model.num_classes)
    elif name in ("MiniTransformer", "TransformerLM"):
        s = max(1, model.seq_len // max(1, seq_scale))
        d = model.d_model
        # per block: x + qkv(3) + attn out + mlp hidden + mlp out
        per_block = b * s * d * (6 + model.mlp_dim // d)
        if not getattr(model, "attn_block", None) and seq_scale == 1:
            # the dense score matrix, unless blockwise/ring streams it
            per_block += b * model.num_heads * s * s
        add(f"{model.num_blocks} blocks",
            model.num_blocks * per_block)
        if hasattr(model, "vocab_size"):
            ce_block = getattr(model, "ce_block", None)
            add("lm_head logits",
                b * min(s, ce_block or s) * model.vocab_size)
        else:
            add("cls_head", b * model.num_classes)
    else:
        raise ValueError(
            f"no activation rule for model type {name!r} — the resource "
            f"budget knows deep_cnn/mlp/resnet*/transformer/lm")
    return rows


def resource_budget(model, optimizer=None, batch_size: int = 1, *,
                    mode: str = "dp", data_ways: int = 1,
                    model_axis: int = 1, zero_level: int = 0,
                    virtual_stages: int = 1,
                    microbatches: int = 0, pp_schedule: str = "auto",
                    zero_overlap: bool = False,
                    zero_bucket_mb: float = 4.0) -> dict:
    """STATIC per-chip memory budget for ``model`` under one parallel
    layout — ``zero_memory_budget`` generalized across the mode matrix
    (``jax.eval_shape``, no chip, no compute): per-leaf param/opt bytes
    with each mode's own sharding divisor (ZeRO chunks over data, PP
    stages blocks, TP follows ``tp_param_specs``, EP the expert-leaf
    rule), transient grad bytes (full leaves in every mode), and a
    coarse activation estimate at the per-chip batch. The live
    ``MemoryMeter`` numbers cross-check against ``per_chip_total``
    (state + grads; activations listed separately — they are transient
    and the cross-check happens between steps)."""
    import math

    import jax
    import numpy as np

    data_ways = max(1, int(data_ways))
    model_axis = max(1, int(model_axis))
    if mode.startswith("zero"):
        zero_level = zero_level or int(mode[4:] or 0)
    params, opt_state = _abstract_state(model, optimizer)
    div_fn = _param_divisor_fn(mode, data_ways, model_axis, zero_level,
                               params)
    rows: list[dict] = []

    from distributed_tensorflow_tpu.utils.pytree import path_key

    def add_rows(kind, tree, divisor_fn, prefix: str = ""):
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            n = math.prod(leaf.shape) if leaf.shape else 1
            isz = np.dtype(leaf.dtype).itemsize
            d = max(1, int(divisor_fn(path, leaf)))
            rows.append({
                "kind": kind,
                "leaf": (prefix + path_key(path)).rstrip("/") or "(scalar)",
                "bytes": n * isz,
                # ceil over ELEMENTS (what the chips actually allocate —
                # padding included, the zero_memory_budget convention)
                "per_chip_bytes": (-(-n // d)) * isz,
                "shard": d,
            })

    add_rows("param", params, div_fn)
    if opt_state is not None:
        pstruct = jax.tree.structure(params)
        # opt slots that mirror the params shard like them; ZeRO-1/3
        # additionally chunks every params-shaped slot over data
        opt_div = div_fn
        if mode in ("zero1", "zero3"):
            opt_div = lambda path, leaf: data_ways

        def walk_opt(entry, prefix: str):
            if jax.tree.structure(entry) == pstruct:
                add_rows("opt", entry, opt_div, prefix=prefix)
            elif isinstance(entry, dict):
                for k, v in entry.items():
                    walk_opt(v, f"{prefix}{k}/")
            else:
                add_rows("opt", entry, lambda p, l: 1, prefix=prefix)

        walk_opt(opt_state, "")

    act_rows = _activation_rows(
        model, -(-int(batch_size) // data_ways),
        seq_scale=model_axis if mode == "sp" else 1)

    def total(kind):
        return sum(r["per_chip_bytes"] for r in rows if r["kind"] == kind)

    p_chip, o_chip = total("param"), total("opt")
    g_chip = sum(r["bytes"] for r in rows if r["kind"] == "param")
    a_chip = sum(r["bytes"] for r in act_rows)
    return {
        "mode": mode, "data_ways": data_ways, "model_axis": model_axis,
        "zero_level": zero_level, "batch_size": int(batch_size),
        "rows": rows, "activation_rows": act_rows,
        "per_chip": {"params": p_chip, "opt": o_chip, "grads": g_chip,
                     "activations": a_chip},
        # the live cross-check target: persistent state + the transient
        # grad leaves every step materializes
        "per_chip_total": p_chip + o_chip + g_chip,
        "per_chip_state_bytes": p_chip + o_chip,
        "param_bytes_full": g_chip,
    }


# ----------------------------------------------------------- comm ledger


def comm_ledger(model, optimizer=None, batch_size: int = 1, *,
                mode: str = "dp", data_ways: int = 1, model_axis: int = 1,
                zero_level: int = 0, virtual_stages: int = 1,
                microbatches: int = 0, pp_schedule: str = "auto",
                zero_overlap: bool = False,
                zero_bucket_mb: float = 4.0,
                ps_wire: str = "f32", ps_mirror: bool = True,
                verify: bool = False) -> dict:
    """STATIC per-step analytic of collective wire bytes for one
    parallel layout, composed from the parallel modules' own row
    builders (the formula lives next to the collective it prices).
    Conventions match the existing docs: all-reduce moves ~2|G|,
    reduce-scatter |G|, all-gather |P|; activation payloads are f32.
    Rows carry ``exposed_bytes`` — the analytic critical-path share:
    ``zero_overlap``/``zero_bucket_mb`` price the ``--zero_overlap``
    bucketed/prefetched pattern, ``pp_schedule`` the tick table (zb's
    cotangent hops overlap the deferred-W slack). Returns {mode,
    rows: [{collective, axis, bytes, exposed_bytes, note}],
    comm_bytes_per_step, comm_exposed_bytes_per_step}.

    The byte accounting is jaxpr-exact as of r18 (``tools/dttcheck``
    proves it against the lowered computation, per mode):

    - ZeRO rows price the PADDED flat chunking (every leaf zero-pads
      to a multiple of D before psum_scatter/all_gather — the padding
      lanes ride the wire like the live ones);
    - the data-axis grad all-reduce prices each rank's ACTUAL payload
      (stage/expert/TP-sharded leaves contribute their 1/K shard, not
      the full leaf);
    - PP/EP/SP rows include the model-axis collectives the old ledger
      missed (replicated-leaf grad psums, the SP grad pmean) and the
      ring rows count every schedule tick/hop the program executes.

    ``verify=True`` machine-proves the returned ledger on the spot:
    the step is traced chip-free over an abstract CPU mesh
    (``tools/dttcheck.verify_ledger``) and any byte drift raises
    ``ValueError`` naming the offending (collective family, axis)
    group. A build/test-time instrument — it needs the repo's
    ``tools/`` on the path and an 8-device CPU mesh."""
    import math

    import jax
    import numpy as np

    data_ways = max(1, int(data_ways))
    model_axis = max(1, int(model_axis))
    if mode.startswith("zero"):
        zero_level = zero_level or int(mode[4:] or 0)
    params, _ = _abstract_state(model, None)
    flat_params = jax.tree_util.tree_flatten_with_path(params)[0]

    def _n(leaf) -> int:
        return math.prod(leaf.shape) if leaf.shape else 1

    param_bytes = sum(_n(l) * np.dtype(l.dtype).itemsize
                      for _, l in flat_params)
    grad_bytes = param_bytes
    # ZeRO's flat chunking zero-pads every leaf to a multiple of D
    # before the scatter/gather — the padding lanes are real wire
    # traffic (dttcheck-proven; the figures are what the chips move)
    padded_bytes = sum(
        (-(-_n(l) // data_ways)) * data_ways * np.dtype(l.dtype).itemsize
        for _, l in flat_params)
    # per-rank payloads for the data-axis all-reduce: sharded leaves
    # (PP stages, EP experts, TP splits) contribute their 1/K shard
    if mode in ("pp", "tp", "ep"):
        div_fn = _param_divisor_fn(mode, data_ways, model_axis,
                                   zero_level, params)
    else:
        div_fn = lambda path, leaf: 1  # noqa: E731
    per_rank_grad_bytes = 0
    rep_grad_bytes = 0
    for path, leaf in flat_params:
        isz = np.dtype(leaf.dtype).itemsize
        d = max(1, int(div_fn(path, leaf)))
        per_rank_grad_bytes += (_n(leaf) // d) * isz
        if d == 1:
            rep_grad_bytes += _n(leaf) * isz
    rows: list[dict] = []

    from distributed_tensorflow_tpu.parallel.zero import zero_comm_rows

    if mode in ("zero1", "zero3"):
        rows += zero_comm_rows(padded_bytes, padded_bytes, zero_level,
                               data_ways, overlap=bool(zero_overlap),
                               bucket_mb=float(zero_bucket_mb or 4.0))
    elif mode == "ps":
        from distributed_tensorflow_tpu.parallel.ps_emulation import (
            ps_comm_rows,
        )

        # per-worker pull/push cycle over the HOST wire, not ICI
        # (``ps_wire``/``ps_mirror`` mirror the --ps_wire/--ps_mirror
        # flags; the pull row is 0 bytes under the mirror cycle)
        rows += ps_comm_rows(param_bytes, grad_bytes,
                             wire=ps_wire, mirror=ps_mirror)
    elif data_ways > 1:
        # every other multi-chip mode pays the plain DP grad all-reduce
        # over its data rows (dp_comm_rows delegates to the one
        # all-reduce formula in zero_comm_rows level 0), at each rank's
        # ACTUAL payload — model-axis-sharded leaves ride at 1/K
        from distributed_tensorflow_tpu.parallel.data_parallel import (
            dp_comm_rows,
        )

        rows += dp_comm_rows(per_rank_grad_bytes, data_ways)

    is_tf = type(model).__name__ in ("MiniTransformer", "TransformerLM")
    seq = getattr(model, "seq_len", 0)
    d_model = getattr(model, "d_model", 0)
    if mode == "pp":
        from distributed_tensorflow_tpu.parallel.pipeline_parallel import (
            pp_comm_rows,
        )

        micro = int(microbatches) or model_axis
        per_shard = -(-int(batch_size) // data_ways)
        act = -(-per_shard // micro) * seq * d_model * F32_BYTES
        rows += pp_comm_rows(act, model_axis, micro,
                             virtual_stages=max(1, int(virtual_stages)),
                             schedule=pp_schedule,
                             rep_grad_bytes=rep_grad_bytes)
    elif mode == "tp" and model_axis > 1:
        from distributed_tensorflow_tpu.parallel.tensor_parallel import (
            tp_comm_rows,
        )

        per_shard = -(-int(batch_size) // data_ways)
        keys = {_path_keys(path) for path, _ in flat_params}
        if is_tf:
            # symmetric boundaries: attention-out + MLP-down per block,
            # each psums a (B, S, d_model) tensor both directions
            act = per_shard * seq * d_model * F32_BYTES
            n_sync = 2 * model.num_blocks
            rows += tp_comm_rows(n_sync * act, n_sync * act)
        elif ("weights", "wd1") in keys:
            # the CNN FC stack: forward psums the row-split OUT
            # matmul's (B, num_classes) partials; backward psums the
            # cotangent at wd1's column-split (B, fc_in) input
            fc_in = next(l.shape[0] for path, l in flat_params
                         if _path_keys(path) == ("weights", "wd1"))
            rows += tp_comm_rows(
                per_shard * model.num_classes * F32_BYTES,
                per_shard * fc_in * F32_BYTES)
        # models without a split table shard nothing -> no TP rows
    elif mode == "ep" and model_axis > 1:
        from distributed_tensorflow_tpu.parallel.expert_parallel import (
            ep_comm_rows,
        )

        per_shard = -(-int(batch_size) // data_ways)
        act = per_shard * seq * d_model * F32_BYTES
        rows += ep_comm_rows(act, getattr(model, "num_blocks", 1),
                             rep_grad_bytes=rep_grad_bytes)
    elif mode == "sp" and model_axis > 1:
        from distributed_tensorflow_tpu.parallel.sequence_parallel import (
            sp_comm_rows,
        )

        per_shard = -(-int(batch_size) // data_ways)
        kv_block = per_shard * (seq // model_axis) * d_model * F32_BYTES
        rows += sp_comm_rows(kv_block, model_axis,
                             getattr(model, "num_blocks", 1),
                             grad_bytes=grad_bytes)

    result = {
        "mode": mode, "data_ways": data_ways, "model_axis": model_axis,
        "rows": rows,
        "comm_bytes_per_step": int(sum(r["bytes"] for r in rows)),
        # rows without an exposure column (TP/EP/SP activation psums)
        # price as fully exposed — the conservative default
        "comm_exposed_bytes_per_step": int(sum(
            r.get("exposed_bytes", r["bytes"]) for r in rows)),
    }
    if verify:
        result["verified"] = _verify_ledger(
            model, optimizer, batch_size, result, mode=mode,
            data_ways=data_ways, model_axis=model_axis,
            zero_level=zero_level, virtual_stages=virtual_stages,
            microbatches=microbatches, pp_schedule=pp_schedule,
            zero_overlap=zero_overlap, zero_bucket_mb=zero_bucket_mb)
    return result


def _verify_ledger(model, optimizer, batch_size, ledger, **cfg) -> bool:
    """The ``comm_ledger(verify=True)`` hook body: trace the REAL step
    for this layout chip-free (tools/dttcheck) and require byte-exact
    agreement; any drift raises ValueError naming the group."""
    import os
    import sys

    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if root not in sys.path:
        sys.path.insert(0, root)
    try:
        from tools.dttcheck import verify_ledger
    except ImportError as e:
        raise RuntimeError(
            f"comm_ledger(verify=True) needs the repo's tools/ tree "
            f"(tools.dttcheck): {e}") from None
    if optimizer is None:
        # the proof needs a runnable update; collective volume does not
        # depend on the optimizer family (grads/slots mirror params)
        from distributed_tensorflow_tpu.training.train_state import sgd

        optimizer = sgd(0.01)
    findings = verify_ledger(model, optimizer, batch_size, ledger, **cfg)
    if findings:
        raise ValueError(
            "comm_ledger(verify=True): the analytic rows do not match "
            "the lowered computation:\n  "
            + "\n  ".join(f.message for f in findings))
    return True


# ---------------------------------------------------- recompile sentry


def batch_signature(batch) -> tuple:
    """The traced signature of a dispatch payload: (shape, dtype) per
    leaf — exactly what jax.jit specializes executables on. Cheap
    (a tree flatten of 1-3 leaves) so the loops can afford it per
    dispatch."""
    import jax

    return tuple(
        (tuple(getattr(a, "shape", ())),
         str(getattr(a, "dtype", type(a).__name__)))
        for a in jax.tree.leaves(batch))


def _sig_delta(old, new) -> str:
    """Human-readable description of what changed between two traced
    signatures — the dimension/dtype the storm report names."""
    if old is None:
        return "first signature"
    try:
        if len(old) != len(new):
            return f"arity {len(old)} -> {len(new)} leaves"
        for i, (o, n) in enumerate(zip(old, new)):
            if o == n:
                continue
            oshape, odt = o if isinstance(o, tuple) and len(o) == 2 \
                else (o, "?")
            nshape, ndt = n if isinstance(n, tuple) and len(n) == 2 \
                else (n, "?")
            if odt != ndt:
                return f"leaf{i} dtype {odt} -> {ndt}"
            if isinstance(oshape, tuple) and isinstance(nshape, tuple):
                if len(oshape) != len(nshape):
                    return (f"leaf{i} rank {len(oshape)} -> "
                            f"{len(nshape)} ({oshape} -> {nshape})")
                for dim, (a, b) in enumerate(zip(oshape, nshape)):
                    if a != b:
                        return (f"leaf{i} dim {dim}: {a} -> {b} "
                                f"(shape {oshape} -> {nshape})")
            return f"leaf{i} {o} -> {n}"
        return "identical (?)"
    except Exception:  # noqa: BLE001 — a weird signature must not crash
        return f"{old!r} -> {new!r}"


class CompileSentry:
    """Counts and times every XLA compile, detects recompiles by traced
    signature, and trips a storm warning past ``--recompile_budget``.

    Two sources, one ledger: the ``jax.monitoring`` backend-compile
    listener (installed once per process, forwarding to the ACTIVE
    sentry) supplies ``compiles_total`` / ``compile_time_s`` — real
    compiles only, cache hits don't fire. ``observe(site, signature)``
    — called by the loops at each dispatch and by the serving engine
    per bucket — supplies the recompile story: the first signature a
    site ever shows is its expected first compile; a NEW signature
    later is a recompile, and the delta (which dim/dtype churned) is
    retained. More than ``budget`` recompiles inside ``window_s``
    seconds prints a loud report naming the churning site and delta,
    drops a ``recompile_storm`` instant span, and dumps the flight
    recorder (the sentinel action-ladder's warn rung). ``budget=0``
    counts but never trips."""

    def __init__(self, budget: int = 0,
                 window_s: float = RECOMPILE_WINDOW_S):
        self.budget = max(0, int(budget))
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        self.compiles_total = 0
        self.compile_time_s = 0.0
        self.recompiles_total = 0
        self.storms = 0
        self._sites: dict = {}       # site -> {sig: hits}
        self._last_sig: dict = {}    # site -> most recent signature
        # (t, site, delta) recompiles inside the storm window. Bounded
        # BY CONSTRUCTION (dttsan SAN004): the window-pruning loop in
        # observe() keeps it small in practice, but a monitoring ring
        # must not rely on pruning logic for its bound — budget+1 is
        # exactly enough for len > budget to trip the storm report
        self._recent: deque = deque(
            maxlen=(self.budget + 1) if self.budget else 1024)
        self.last_delta: str | None = None

    def on_compile_event(self, event: str, dur: float) -> None:
        if not event.endswith("backend_compile_duration"):
            return
        with self._lock:
            self.compiles_total += 1
            self.compile_time_s += float(dur)

    def site_signatures(self, site: str) -> int:
        with self._lock:
            return len(self._sites.get(site, ()))

    def observe(self, site: str, signature) -> str | None:
        """Record one dispatch; returns the delta string when this was
        a recompile (a NEW signature on a known site), else None."""
        storm = None
        with self._lock:
            sigs = self._sites.setdefault(site, {})
            if signature in sigs:
                sigs[signature] += 1
                return None
            prev = self._last_sig.get(site)
            sigs[signature] = 1
            self._last_sig[site] = signature
            # bound the ledger: a client-controlled signature axis
            # (e.g. serve_decode's per-request max_new_tokens) must not
            # grow the MONITORING plane without limit in a long-lived
            # replica — evict oldest-first (a re-seen evicted signature
            # counts as a recompile again, which is the honest reading:
            # its executable likely aged out of jit's cache too)
            if len(sigs) > MAX_SIGS_PER_SITE:
                sigs.pop(next(iter(sigs)))
            if prev is None:
                return None  # the site's expected first compile
            self.recompiles_total += 1
            delta = _sig_delta(prev, signature)
            self.last_delta = f"{site}: {delta}"
            now = time.monotonic()
            self._recent.append((now, site, delta))
            while self._recent and now - self._recent[0][0] > self.window_s:
                self._recent.popleft()
            if self.budget and len(self._recent) > self.budget:
                storm = (site, delta, len(self._recent))
                self._recent.clear()  # one report per storm incident
                self.storms += 1
        if storm is not None:
            self._report_storm(*storm)
        return delta

    def _report_storm(self, site: str, delta: str, count: int) -> None:
        line = "=" * 70
        print(f"\n{line}\nRECOMPILE STORM: {count} recompiles inside "
              f"{self.window_s:.0f}s (budget {self.budget}) — latest at "
              f"site {site!r}: {delta}\n"
              f"  every new traced signature costs a full XLA compile; "
              f"a churning batch/bucket shape turns the step budget "
              f"into compile time (pad to stable buckets — the serving "
              f"power-of-two bucketing and schedules.py exist for "
              f"this)\n{line}", flush=True)
        telemetry.get_tracer().record_instant(
            "recompile_storm", site=site, delta=delta, count=count,
            budget=self.budget)
        telemetry.flight_recorder().dump(f"recompile_storm:{site}")

    def scalars(self) -> dict:
        with self._lock:
            return {
                "compiles_total": float(self.compiles_total),
                "compile_time_s": round(self.compile_time_s, 4),
                "recompiles_total": float(self.recompiles_total),
            }


# one process-wide listener forwarding to the ACTIVE sentry (the
# monitoring API has no unregister; the indirection makes re-runs and
# tests safe — swap the sentry, not the listener)
_ACTIVE: dict = {"meter": None, "sentry": None, "budget": None}
_ACTIVE_LOCK = threading.Lock()
_LISTENER = {"installed": False}


def _install_compile_listener() -> None:
    with _ACTIVE_LOCK:
        if _LISTENER["installed"]:
            return
        _LISTENER["installed"] = True
    try:
        import jax

        def _on_duration(event, duration, **kw):
            s = _ACTIVE.get("sentry")
            if s is not None:
                s.on_compile_event(event, duration)

        jax.monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception as e:  # noqa: BLE001 — no jax, no compile events
        print(f"resources: compile listener unavailable: {e}")


def activate(meter: MemoryMeter | None = None,
             sentry: CompileSentry | None = None,
             budget: dict | None = None) -> None:
    """Install the instruments the process-wide hooks (compile
    listener, OOM excepthook, checkpoint sample notes) forward to.
    Passing None clears a slot."""
    with _ACTIVE_LOCK:
        _ACTIVE["meter"] = meter
        _ACTIVE["sentry"] = sentry
        _ACTIVE["budget"] = budget


def active_meter() -> MemoryMeter | None:
    return _ACTIVE.get("meter")


def active_sentry() -> CompileSentry | None:
    return _ACTIVE.get("sentry")


def note_signature(site: str, signature) -> None:
    """Module-level dispatch note for layers that don't hold a monitor
    (the serving engine) — forwards to the active sentry, no-op
    otherwise."""
    s = _ACTIVE.get("sentry")
    if s is not None:
        s.observe(site, signature)


def sample_note(tag: str) -> None:
    """One memory sample attributed to a named boundary (checkpoint
    save/restore — the big allocation events); no-op without an active
    meter. Never raises."""
    m = _ACTIVE.get("meter")
    if m is None:
        return
    try:
        m.sample(tag=tag)
    except Exception:  # noqa: BLE001 — accounting never kills a run
        pass


# -------------------------------------------------------- OOM postmortem


def _is_oom(exc_type, exc) -> bool:
    name = getattr(exc_type, "__name__", "")
    text = f"{name}: {exc}"
    return "XlaRuntimeError" in name or any(s in text for s in OOM_SIGNS)


def _top_live_buffers(n: int = TOP_LIVE_BUFFERS) -> list[dict]:
    """The N largest live jax arrays (shape/dtype/bytes) — which
    buffers actually hold the memory when the allocator gives up."""
    try:
        import jax

        rows = [{"shape": list(getattr(a, "shape", ())),
                 "dtype": str(getattr(a, "dtype", "?")),
                 "nbytes": int(getattr(a, "nbytes", 0))}
                for a in jax.live_arrays()]
        rows.sort(key=lambda r: -r["nbytes"])
        return rows[:n]
    except Exception:  # noqa: BLE001 — the postmortem must still land
        return []


def oom_postmortem(exc=None, reason: str | None = None) -> str | None:
    """Record the OOM story into the flight ring — the last memory
    samples are already there (every ``hbm_sample`` instant rides it);
    this adds the analytic budget table and the top-N largest live
    buffers — then dump. Returns the flightrec path (None when no sink
    is configured). Safe to call from any layer on any suspected-OOM
    error; the chained excepthook calls it automatically."""
    fr = telemetry.flight_recorder()
    fr.record("note", {
        "note": f"OOM postmortem: "
                f"{type(exc).__name__ if exc is not None else 'manual'}: "
                f"{str(exc)[:400]}"})
    m = _ACTIVE.get("meter")
    if m is not None:
        try:
            m.sample(tag="oom")  # one last reading, if the runtime answers
        except Exception:  # noqa: BLE001
            pass
    budget = _ACTIVE.get("budget")
    if budget:
        top = sorted(budget.get("rows", ()),
                     key=lambda r: -r["per_chip_bytes"])[:TOP_LIVE_BUFFERS]
        fr.record("hbm_budget", {
            "mode": budget.get("mode"),
            "per_chip": budget.get("per_chip"),
            "per_chip_total": budget.get("per_chip_total"),
            "activation_bytes": sum(
                r["bytes"] for r in budget.get("activation_rows", ())),
            "largest_leaves": [
                {"leaf": r["leaf"], "kind": r["kind"],
                 "per_chip_bytes": r["per_chip_bytes"]} for r in top],
        })
    for row in _top_live_buffers():
        fr.record("live_buffer", row)
    return fr.dump(reason or (
        f"oom:{type(exc).__name__}" if exc is not None else "oom:manual"))


_OOM_HOOK = {"installed": False}


def install_oom_hook() -> None:
    """Chain an OOM recognizer onto ``sys.excepthook`` (in front of the
    telemetry flight-recorder hook, which installed first): a crashing
    ``XlaRuntimeError``/RESOURCE_EXHAUSTED enriches the ring with the
    budget table and largest live buffers BEFORE the postmortem dump,
    so the OOM is diagnosable from flightrec-*.jsonl alone. Idempotent."""
    with _ACTIVE_LOCK:
        if _OOM_HOOK["installed"]:
            return
        _OOM_HOOK["installed"] = True
    prev = sys.excepthook

    def _hook(exc_type, exc, tb):
        try:
            if _is_oom(exc_type, exc):
                oom_postmortem(exc)
        except Exception:  # noqa: BLE001 — never mask the real crash
            pass
        prev(exc_type, exc, tb)

    sys.excepthook = _hook


# ------------------------------------------------------ monitor + flags


class ResourceMonitor:
    """The loops' one-stop resource accountant: bundles the memory
    meter, the compile sentry, and the comm ledger behind the two calls
    the loops make — ``scalars()`` at the display cadence and
    ``note_dispatch(site, batch|signature)`` per dispatch."""

    def __init__(self, meter: MemoryMeter | None,
                 sentry: CompileSentry | None,
                 ledger: dict | None):
        self.meter = meter
        self.sentry = sentry
        self.ledger = ledger

    def scalars(self) -> dict:
        out: dict = {}
        if self.meter is not None:
            out.update(self.meter.scalars())
        if self.sentry is not None:
            out.update(self.sentry.scalars())
        if self.ledger is not None:
            out["comm_bytes_per_step"] = float(
                self.ledger["comm_bytes_per_step"])
            out["comm_exposed_bytes_per_step"] = float(
                self.ledger.get("comm_exposed_bytes_per_step",
                                self.ledger["comm_bytes_per_step"]))
        return out

    def note_dispatch(self, site: str, batch=None, signature=None) -> None:
        if self.sentry is None:
            return
        sig = signature if signature is not None else batch_signature(batch)
        self.sentry.observe(site, sig)


def parallel_config_from_flags(FLAGS, n_chips: int) -> dict:
    """Derive the budget/ledger layout config from the parsed flags —
    the one flags->layout mapping the loops, bench, and tools share."""
    model_axis = max(1, int(getattr(FLAGS, "model_axis", 1) or 1))
    zero = int(getattr(FLAGS, "zero", 0) or 0)
    if zero:
        mode, model_axis = f"zero{zero}", 1
    elif getattr(FLAGS, "pipeline", False):
        mode = "pp"
    elif getattr(FLAGS, "expert_parallel", False):
        mode = "ep"
    elif getattr(FLAGS, "seq_parallel", False):
        mode = "sp"
    elif model_axis > 1:
        mode = "tp"
    else:
        mode = "dp"
    return {
        "mode": mode,
        "data_ways": max(1, int(n_chips) // model_axis),
        "model_axis": model_axis,
        "zero_level": zero,
        "virtual_stages": max(1, int(getattr(FLAGS, "virtual_stages", 1)
                                     or 1)),
        "microbatches": int(getattr(FLAGS, "pp_microbatches", 0) or 0),
        "pp_schedule": getattr(FLAGS, "pp_schedule", "auto") or "auto",
        "zero_overlap": bool(getattr(FLAGS, "zero_overlap", False)),
        "zero_bucket_mb": float(getattr(FLAGS, "zero_bucket_mb", 4.0)
                                or 4.0),
    }


def monitor_from_flags(FLAGS, model, optimizer, batch_size: int,
                       n_chips: int,
                       model_axis: int | None = None) -> ResourceMonitor | None:
    """The one flag->feature mapping for the resource plane
    (``--hbm_sample_every`` / ``--recompile_budget``), shared by every
    training loop and the serving entry point. None under
    ``--telemetry=false`` (the plane rides the spine — its samples,
    storm spans, and postmortems are all telemetry artifacts).
    Installs the process-wide hooks (compile listener, OOM excepthook)
    and emits the ``comm_ledger`` instant span the fleet report reads.

    ``model_axis`` overrides the flag-derived layout with an explicit
    TP degree — the serving entry point passes ``--serve_tp`` (a
    TP-sharded replica's budget must price the 1/K params each chip
    actually holds, not the training namespace's --model_axis)."""
    if not bool(getattr(FLAGS, "telemetry", True)):
        return None
    cfg = parallel_config_from_flags(FLAGS, n_chips)
    if model_axis is not None and int(model_axis) > 1:
        cfg.update(mode="tp", model_axis=int(model_axis),
                   data_ways=max(1, int(n_chips) // int(model_axis)),
                   zero_level=0)
    budget = ledger = None
    try:
        budget = resource_budget(model, optimizer, batch_size, **cfg)
    except Exception as e:  # noqa: BLE001 — accounting never blocks a run
        print(f"resource accounting: analytic budget unavailable: {e}")
    if optimizer is not None:
        # the ledger prices a TRAINING step's collectives; a serving
        # caller (no optimizer) has no grad traffic to price
        try:
            ledger = comm_ledger(model, optimizer, batch_size, **cfg)
        except Exception as e:  # noqa: BLE001
            print(f"resource accounting: comm ledger unavailable: {e}")
    sample_every = int(getattr(FLAGS, "hbm_sample_every", 1) or 0)
    # the cross-check anchor is the PERSISTENT state (params+opt):
    # samples land at display boundaries, between steps, where grads
    # and activations are transient (and --device_data's resident
    # split is a documented live-over-analytic delta)
    meter = (MemoryMeter(
        analytic_bytes=budget["per_chip_state_bytes"] if budget else None,
        sample_every=sample_every) if sample_every > 0 else None)
    sentry = CompileSentry(
        budget=int(getattr(FLAGS, "recompile_budget", 0) or 0))
    _install_compile_listener()
    install_oom_hook()
    activate(meter=meter, sentry=sentry, budget=budget)
    if ledger is not None:
        telemetry.get_tracer().record_instant(
            "comm_ledger", mode=ledger["mode"],
            comm_bytes_per_step=ledger["comm_bytes_per_step"],
            data_ways=ledger["data_ways"],
            model_axis=ledger["model_axis"])
    return ResourceMonitor(meter, sentry, ledger)
