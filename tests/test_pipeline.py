"""Prefetch pipeline: ordering, error propagation, clean shutdown."""

import threading
import time

import numpy as np
import pytest

from distributed_tensorflow_tpu.data.pipeline import batch_iterator, prefetch_to_device
from distributed_tensorflow_tpu.data.datasets import DataSet


def test_prefetch_preserves_order_and_values():
    batches = [(np.full((2, 2), i), np.array([i])) for i in range(5)]
    out = list(prefetch_to_device(iter(batches)))
    assert len(out) == 5
    for i, (x, y) in enumerate(out):
        np.testing.assert_allclose(np.asarray(x), i)


def test_prefetch_propagates_worker_exception():
    def gen():
        yield (np.zeros(1), np.zeros(1))
        raise RuntimeError("boom in loader")

    it = prefetch_to_device(gen())
    next(it)
    with pytest.raises(RuntimeError, match="boom in loader"):
        next(it)


def test_prefetch_close_terminates_worker():
    before = threading.active_count()

    def infinite():
        i = 0
        while True:
            yield (np.full(4, i), np.zeros(1))
            i += 1

    it = prefetch_to_device(infinite(), size=2)
    next(it)
    it.close()
    deadline = time.time() + 5
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.02)
    assert threading.active_count() <= before


def test_empty_dataset_next_batch_raises():
    ds = DataSet(np.zeros((0, 4), np.float32), np.zeros(0, np.int64))
    with pytest.raises(ValueError, match="empty"):
        ds.next_batch(4)


def test_batch_iterator_shapes():
    ds = DataSet(np.arange(20, dtype=np.float32).reshape(10, 2),
                 np.zeros(10, np.int64), one_hot=True)
    it = batch_iterator(ds, 4)
    x, y = next(it)
    assert x.shape == (4, 2) and y.shape == (4, 10)
