"""Worker subprocess for the multi-host sync-DP test (not a pytest file).

Each invocation is one "host": a process owning 4 virtual CPU devices that
joins a 2-process jax.distributed cluster over localhost, builds the global
8-device mesh, feeds its own slice of every global batch, trains 5 sync-DP
steps, and dumps its final params. The pytest side asserts params are
identical across processes and equal to a single-process 8-device run —
the determinism property the reference's async mode gives up and this
build's sync mode guarantees (SURVEY.md §2c).

Usage: python multihost_worker.py <step|train> <process_id> <num_processes> <port> <outdir>

"step"  — hand-rolled 5-step run on deterministic batches (params dumped
          for cross-process / vs-single-process comparison)
"train" — the PRODUCTION loop: training.loop.train(mode="sync") end to end
          (prefetch pipeline, supervisor, per-process dataset seeds, the
          cross-process stop-vote), asserting it completes.
"""

import os
import sys
import time

import numpy as np

GLOBAL_BATCH = 16
STEPS = 5
LR = 0.05


def make_batch(i: int, n: int):
    """Deterministic global batch i — identical on every process."""
    rng = np.random.default_rng(1000 + i)
    x = rng.random((n, 784), np.float32)
    y = np.zeros((n, 10), np.float32)
    y[np.arange(n), rng.integers(0, 10, n)] = 1.0
    return x, y


def _init_cluster(process_id: int, num_processes: int, port: str,
                  local_devices: int = 4):
    # virtual CPU platform BEFORE backend init (conftest recipe:
    # config-update beats a sitecustomize JAX_PLATFORMS pin, env alone loses)
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={local_devices}")
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_matmul_precision", "highest")
    try:
        # newer jaxlib defaults CPU collectives to "none" — every
        # cross-host psum would raise; gloo is the multi-process CPU path
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=num_processes,
        process_id=process_id,
    )
    assert jax.process_count() == num_processes
    assert jax.local_device_count() == local_devices
    assert jax.device_count() == local_devices * num_processes
    return jax


def run_train_loop(process_id: int, num_processes: int, port: str, outdir: str,
                   extra_flags: tuple = (), local_devices: int = 4,
                   training_iter: int = 12) -> None:
    """Production path: flags + train(mode="sync") across 2 processes."""
    jax = _init_cluster(process_id, num_processes, port, local_devices)

    from distributed_tensorflow_tpu import flags
    from distributed_tensorflow_tpu.training.loop import train

    flags.define_reference_flags()
    flags.FLAGS._parse([
        f"--logdir={outdir}/logs",
        f"--data_dir={outdir}/no-data",  # forces synthetic
        f"--training_iter={training_iter}",
        "--batch_size=32",
        "--display_step=4",
        "--optimizer=adam",
        "--learning_rate=0.002",
        "--save_model_secs=100000",
        f"--task_index={process_id}",
        *extra_flags,
    ])
    res = train(flags.FLAGS, mode="sync")
    assert res.final_step == training_iter, res
    assert res.n_chips == local_devices * num_processes, res
    print(f"TRAIN_OK p{process_id} step={res.final_step}", flush=True)
    jax.distributed.shutdown()


def run_train_device(process_id: int, num_processes: int, port: str, outdir: str) -> None:
    """--device_data across processes: the split replicated onto the global
    mesh via make_array_from_process_local_data, chunked on-device steps."""
    run_train_loop(process_id, num_processes, port, outdir,
                   ("--device_data", "--device_chunk=4"))


def run_train_straggler(process_id: int, num_processes: int, port: str,
                        outdir: str) -> None:
    """Straggler chaos (r12): a --fault_spec prefetch delay armed on
    process 1 ONLY makes every one of its host batches ~40 ms late —
    the slow-host signature. The vote's work_us column must then name
    process 1 in the chief's step_skew_s/straggler_host scalars, and
    both hosts' span files (+ coord_clock markers) must let
    tools/fleet_report.py attribute the same straggler offline."""
    extra = ["--coord_steps=4", "--model=mlp", "--keep_prob=1.0"]
    if process_id == 1:
        # 150 ms per staged batch: far above an MLP step, so the
        # prefetch queue can never hide it and host_wait balloons
        extra.append(
            "--fault_spec=prefetch:mode=delay:delay=0.15:times=0")
    run_train_loop(process_id, num_processes, port, outdir,
                   tuple(extra), training_iter=24)


def run_train_tp(process_id: int, num_processes: int, port: str, outdir: str) -> None:
    """--model_axis=2 across processes: TP+DP over the global mesh, state
    placed per-host via make_array_from_callback (shard_state_tp)."""
    run_train_loop(process_id, num_processes, port, outdir,
                   ("--model_axis=2",))


def run_train_tp_span(process_id: int, num_processes: int, port: str,
                      outdir: str) -> None:
    """The round-2 latent crash shape: 2 processes x 2 devices with
    --model_axis=4, so FC shards live on devices this process cannot
    address and NO host holds full local coverage. Exercises the
    coordinated checkpoint path end to end: the cadenced vote triggers a
    mid-run collective save (save_model_secs=1 elapses during compile;
    the first --coord_steps boundary lands it), and the managed-exit
    final save gathers the spanning leaves via process_allgather."""
    run_train_loop(process_id, num_processes, port, outdir,
                   ("--model_axis=4", "--save_model_secs=1",
                    "--coord_steps=4", "--eval_step=20"),
                   local_devices=2, training_iter=40)


def run_train_kill(process_id: int, num_processes: int, port: str,
                   outdir: str) -> None:
    """SIGTERM one host mid-run: the stop must propagate through the
    cadenced vote so BOTH processes exit at the same agreed step and the
    chief's final checkpoint lands at that step (the Supervisor
    survive-and-checkpoint contract under the post-round-2 cadenced
    protocol — no per-iteration allgather to lean on anymore)."""
    import signal
    import threading

    jax = _init_cluster(process_id, num_processes, port)

    from distributed_tensorflow_tpu import flags
    from distributed_tensorflow_tpu.training.loop import train

    flags.define_reference_flags()
    flags.FLAGS._parse([
        f"--logdir={outdir}/logs",
        f"--data_dir={outdir}/no-data",
        "--training_iter=20000",  # safety cap; the kill ends the run
        "--batch_size=32",
        "--display_step=10000",
        "--model=mlp",  # fast CPU steps: the test targets the protocol
        "--save_model_secs=100000",  # no cadenced saves: final save only
        "--coord_steps=5",
        "--test_eval=false",
        f"--task_index={process_id}",
    ])
    if process_id == 1:
        # the NON-chief gets the signal; only the vote can tell the chief.
        # Fire only once training is observably underway (the chief's
        # metrics file appears at the step-0 display, which both processes
        # have synced past via the display eval's collective) — a fixed
        # delay races managed()'s handler install and a SIGTERM landing
        # before it hits whatever disposition the environment left.
        metrics = os.path.join(outdir, "logs", "metrics.jsonl")

        def _kill_when_training():
            while not os.path.exists(metrics):
                time.sleep(0.25)
            time.sleep(2.0)
            os.kill(os.getpid(), signal.SIGTERM)

        threading.Thread(target=_kill_when_training, daemon=True).start()
    res = train(flags.FLAGS, mode="sync")
    assert res.final_step < 20000, f"kill did not interrupt: {res}"
    print(f"KILL_OK p{process_id} step={res.final_step}", flush=True)
    jax.distributed.shutdown()


def run_train_sp(process_id: int, num_processes: int, port: str,
                 outdir: str) -> None:
    """--seq_parallel across 2 processes: batch sliced per host (data
    axis spans processes), the token axis sharded within each host's 4
    devices, ring attention over the global mesh's "model" axis, batch
    slices assembled via make_array_from_process_local_data."""
    run_train_loop(process_id, num_processes, port, outdir,
                   ("--seq_parallel", "--model=transformer",
                    "--model_axis=4"))


def run_train_sp_lm(process_id: int, num_processes: int, port: str,
                    outdir: str) -> None:
    """--seq_parallel --model lm across 2 processes: per-token targets
    sharded WITH their tokens, causal ring attention over the
    within-host token axis, the per-token uniform-pmean reduction, and
    the chief's final checkpoint (SP state replicates, so this is the
    monolithic format — the sharded format's multihost coverage lives
    in train_tp_span, whose leaves actually span hosts)."""
    run_train_loop(process_id, num_processes, port, outdir,
                   ("--seq_parallel", "--model=lm", "--dataset=lm",
                    "--model_axis=4", "--seq_len=32", "--vocab_size=16",
                    "--d_model=32", "--num_heads=2", "--num_blocks=1"))


def run_train_sp_span(process_id: int, num_processes: int, port: str,
                      outdir: str) -> None:
    """--sp_span_hosts: the token axis SPANS both processes (model_axis=8
    over 2 procs x 4 devices — ring hops cross the process boundary on
    every attention), every process draws the SAME global batch and
    uploads only its tile. The pytest side compares the final
    checkpoint against a single-process 8-device run of the identical
    config — the span must be a pure layout change."""
    run_train_loop(process_id, num_processes, port, outdir,
                   ("--seq_parallel", "--sp_span_hosts", "--model=lm",
                    "--dataset=lm", "--model_axis=8", "--seq_len=32",
                    "--vocab_size=16", "--d_model=32", "--num_heads=2",
                    "--num_blocks=1", "--keep_prob=1.0", "--seed=7"))


def run_span_mixed_exit(process_id: int, num_processes: int, port: str,
                        outdir: str) -> None:
    """The r3 ADVICE mixed-exit hole: cross-host-sharded state, process 1
    raises inside managed() while process 0 exits cleanly. Before the
    exit-agreement gate, p0 entered the final save's process_allgather
    that p1 (skipping on error) never joined — hanging p0 forever. Now
    BOTH processes join one bounded agreement allgather of clean flags,
    see the mixed verdict, and skip the save symmetrically: p0 exits 0
    with the skip message, p1 exits nonzero with the original error."""
    jax = _init_cluster(process_id, num_processes, port, local_devices=2)
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_tpu.parallel import MeshSpec, make_mesh
    from distributed_tensorflow_tpu.training.supervisor import Supervisor

    mesh = make_mesh(MeshSpec(data=1, model=4))
    full = np.arange(8.0, dtype=np.float32)
    w = jax.make_array_from_callback(
        (8,), NamedSharding(mesh, P("model")), lambda idx: full[idx])

    sup = Supervisor(is_chief=(process_id == 0),
                     logdir=os.path.join(outdir, "logs"),
                     save_model_secs=10**6)
    try:
        with sup.managed({"w": w, "step": np.int64(0)}) as box:
            box.update({"w": w, "step": np.int64(3)}, 3)
            if process_id == 1:
                raise RuntimeError("injected failure before clean exit")
    except RuntimeError:
        print(f"MIXED_EXIT_RAISED p{process_id}", flush=True)
        jax.distributed.shutdown()
        sys.exit(7)
    print(f"MIXED_EXIT_CLEAN p{process_id}", flush=True)
    jax.distributed.shutdown()


def run_train_crash(process_id: int, num_processes: int, port: str,
                    outdir: str) -> None:
    """The r8 crash-restart chaos worker: the PRODUCT's cluster-join path
    (cluster.maybe_initialize_distributed with bounded retry/backoff —
    not the test-harness direct jax.distributed.initialize), then the
    --device_data production loop. Faults arrive via the DTT_FAULT_SPEC
    env var (the pytest side arms ckpt_write:mode=crash on the chief for
    the crash phase, init:mode=refuse:times=1 on the relaunched worker to
    pin the retry path). --device_data makes the trajectory a pure
    function of the checkpointed state (batches sampled on device from
    state.rng), so a crashed-and-relaunched run's final params must match
    an uninterrupted run's BITWISE."""
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_matmul_precision", "highest")

    from distributed_tensorflow_tpu.cluster import (
        ClusterSpec,
        maybe_initialize_distributed,
    )

    # only workers[0] (the coordinator address) and the count matter
    spec = ClusterSpec({"ps": [], "worker": [
        f"127.0.0.1:{port}"] + ["127.0.0.1:1"] * (num_processes - 1)})
    maybe_initialize_distributed(spec, process_id, init_retries=12,
                                 init_backoff_s=0.5, init_timeout_s=20)
    assert jax.process_count() == num_processes

    from distributed_tensorflow_tpu import flags
    from distributed_tensorflow_tpu.training.loop import train

    flags.define_reference_flags()
    flags.FLAGS._parse([
        f"--logdir={outdir}/logs",
        f"--data_dir={outdir}/no-data",
        "--training_iter=24",
        "--batch_size=32",
        "--display_step=4",
        "--model=mlp",
        "--device_data",
        "--device_chunk=4",
        "--optimizer=adam",
        "--learning_rate=0.002",
        "--save_model_secs=1",  # first coord boundary lands a save
        "--coord_steps=4",
        "--test_eval=false",
        f"--task_index={process_id}",
    ])
    res = train(flags.FLAGS, mode="sync")
    assert res.final_step == 24, res
    print(f"CRASH_RUN_OK p{process_id} step={res.final_step}", flush=True)
    jax.distributed.shutdown()


def run(process_id: int, num_processes: int, port: str, outdir: str) -> None:
    jax = _init_cluster(process_id, num_processes, port)

    from distributed_tensorflow_tpu.models import DeepCNN
    from distributed_tensorflow_tpu.parallel import (
        MeshSpec,
        make_dp_train_step,
        make_mesh,
        shard_batch,
    )
    from distributed_tensorflow_tpu.parallel.data_parallel import (
        local_batch_size,
        replicate_state,
    )
    from distributed_tensorflow_tpu.training import create_train_state, sgd

    mesh = make_mesh(MeshSpec(data=jax.device_count(), model=1))
    model = DeepCNN()
    opt = sgd(LR)
    state = replicate_state(mesh, create_train_state(model, opt, seed=0))
    step_fn = make_dp_train_step(model, opt, mesh, keep_prob=1.0, donate=False)

    local = local_batch_size(GLOBAL_BATCH)
    lo = process_id * local
    snapshots = {}
    for i in range(STEPS):
        x, y = make_batch(i, GLOBAL_BATCH)
        # this process's slice only — shard_batch assembles the global array
        batch = shard_batch(mesh, (x[lo : lo + local], y[lo : lo + local]))
        state, metrics = step_fn(state, batch)
        if i == 0:
            # step-1 snapshot: compared tightly against the single-process
            # run (before chaotic float divergence can amplify the
            # all-reduce's different reduction order)
            leaves, _ = jax.tree_util.tree_flatten(jax.device_get(state.params))
            snapshots.update(
                {f"step1_leaf_{j}": np.asarray(l) for j, l in enumerate(leaves)}
            )
    jax.block_until_ready(state.params)
    assert int(state.step) == STEPS

    leaves, _ = jax.tree_util.tree_flatten(jax.device_get(state.params))
    snapshots.update({f"leaf_{j}": np.asarray(l) for j, l in enumerate(leaves)})
    np.savez(
        os.path.join(outdir, f"params_p{process_id}.npz"),
        **snapshots,
        loss=np.float32(metrics["loss"]),
    )
    jax.distributed.shutdown()


if __name__ == "__main__":
    mode = sys.argv[1]
    fn = {"step": run, "train": run_train_loop,
          "train_straggler": run_train_straggler,
          "train_device": run_train_device, "train_tp": run_train_tp,
          "train_tp_span": run_train_tp_span,
          "train_sp": run_train_sp,
          "train_sp_lm": run_train_sp_lm,
          "train_sp_span": run_train_sp_span,
          "span_mixed_exit": run_span_mixed_exit,
          "train_kill": run_train_kill,
          "train_crash": run_train_crash}[mode]
    fn(int(sys.argv[2]), int(sys.argv[3]), sys.argv[4], sys.argv[5])
