"""InferenceEngine: checkpoint-to-traffic, with hot-reload.

The training half of the lifecycle ends at a CRC-manifested checkpoint
directory; this engine is the other half. It restores the ``params``
field of the newest TrainState checkpoint through the SAME
verify–quarantine–fallback ladder the trainer's restore uses
(``checkpoint.restore_params_with_fallback`` — a corrupt newest set is
quarantined and the newest older complete set serves instead), places
the params with the existing mesh machinery (DP-replicated or
TP-sharded via ``parallel/tensor_parallel.tp_param_specs``), and serves
through jitted apply functions with power-of-two batch bucketing (one
cached executable per padded shape) and float input buffers donated.

Hot-reload (TF-Serving's checkpoint-watch/swap model): a
``CheckpointWatcher`` thread polls the directory; a newer step restores
through the ladder OFF the serving path, is placed, and the params
reference swaps atomically between microbatches — in-flight batches
hold the reference they started with, so nothing is dropped. A newest
set that turns out corrupt rides the ladder down and the engine keeps
serving what it has (``serve_reload`` fault point; tests tear the
newest file there and assert zero dropped requests).

``jit=False`` is the host-only mode: no jax backend is touched — the
restore, swap, and bucket machinery run pure-numpy against any object
with ``apply(params, x)``. bench.py's serving phase uses it so serving
latency/reload evidence survives chip outages, exactly like the
recovery drill.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from distributed_tensorflow_tpu.checkpoint.checkpoint import (
    latest_checkpoint,
    restore_params_with_fallback,
)
from distributed_tensorflow_tpu.serving import reqtrace
from distributed_tensorflow_tpu.utils import resources
from distributed_tensorflow_tpu.utils.faults import fault_point
from distributed_tensorflow_tpu.utils.telemetry import trace_span


class NoCheckpointError(FileNotFoundError):
    """Serving needs weights: raised when the logdir holds no restorable
    checkpoint at engine construction."""


class InferenceEngine:
    """Loads, places, serves, and hot-swaps one model's parameters.

    ``mesh=None`` serves on the default device; with a mesh, ``tp=False``
    replicates the params over every chip (DP serving — each request
    batch can split over the data axis) and ``tp=True`` shards them with
    the Megatron block split (``tp_param_specs``), XLA deriving the
    collectives. ``params_template`` defaults to ``model.init`` (jax
    path); host-mode callers pass it explicitly.
    """

    def __init__(self, model, logdir: str, *, mesh=None, tp: bool = False,
                 jit: bool = True, params_template=None,
                 max_batch: int = 8):
        self.model = model
        self.logdir = logdir
        self.mesh = mesh
        self.tp = bool(tp)
        self.jit = bool(jit)
        self.max_batch = int(max_batch)
        # token-id models (anything with a vocab) take int32 ids; dense
        # models take floats — the wire always delivers JSON numbers, so
        # the engine owns the cast
        self.input_dtype = (np.int32 if hasattr(model, "vocab_size")
                            else np.float32)
        self._swap_lock = threading.Lock()
        # one reload in flight at a time: the watcher tick and a
        # check_now() caller racing each other could both restore, and
        # the slower (older) restore would swap AFTER the newer one —
        # serving a version regression. The swap lock stays the cheap
        # read-side guard; this serializes the whole restore+swap.
        self._reload_lock = threading.Lock()
        self._fn_lock = threading.Lock()
        self._apply_cache: dict = {}
        self._decode_cache: dict = {}
        self._params = None
        self._step = -1
        # counters are mutated on the watcher thread (_reload) and read
        # from HTTP handler threads (/metrics, /stats) and the batcher
        # worker (ServingMetrics) — guarded by _swap_lock like _step;
        # readers take counters_snapshot()
        self.counters = {"reloads": 0, "reload_failures": 0,
                         "reload_fallbacks": 0, "last_reload_ms": 0.0,
                         "last_fallback_depth": 0}
        if params_template is None:
            import jax

            params_template = model.init(jax.random.PRNGKey(0))
        self._template = params_template
        out = restore_params_with_fallback(logdir, self._template)
        if out is None:
            raise NoCheckpointError(
                f"no restorable checkpoint in {logdir!r} — serving needs "
                f"trained weights")
        params, step, report = out
        self._params = self._place(params)
        self._step = step
        self.restore_report = report

    # ------------------------------------------------------- placement

    def _place(self, params):
        if not self.jit:
            return params
        import jax

        if self.mesh is None:
            return jax.device_put(params)
        from jax.sharding import NamedSharding, PartitionSpec as P

        from distributed_tensorflow_tpu.parallel.tensor_parallel import (
            _check_divisibility,
            _map_specs,
            tp_param_specs,
        )

        if self.tp:
            specs = tp_param_specs(params)
            _check_divisibility(params, specs, self.mesh)
            return jax.device_put(params,
                                  _map_specs(params, specs, self.mesh))
        return jax.device_put(
            params, jax.tree.map(
                lambda _: NamedSharding(self.mesh, P()), params))

    def _stage(self, x):
        """Input placement: batch split over the data axis when the
        bucket divides it, else replicated (tiny buckets)."""
        import jax

        if self.mesh is None:
            return jax.device_put(x)
        from jax.sharding import NamedSharding, PartitionSpec as P

        from distributed_tensorflow_tpu.parallel.mesh import (
            DATA_AXIS,
            batch_sharding,
        )

        if x.shape[0] % self.mesh.shape[DATA_AXIS] == 0:
            return jax.device_put(x, batch_sharding(self.mesh, x.ndim))
        return jax.device_put(x, NamedSharding(self.mesh, P()))

    # --------------------------------------------------------- serving

    def current(self):
        """(params, step) — the batch worker reads this ONCE per
        microbatch; a concurrent hot-swap changes what the NEXT batch
        sees, never the one in flight."""
        with self._swap_lock:
            return self._params, self._step

    @property
    def step(self) -> int:
        with self._swap_lock:
            return self._step

    def counters_snapshot(self) -> dict:
        """One consistent copy of the reload counters — what /metrics,
        /stats and the serving scalar cadence read while the watcher
        thread reloads."""
        with self._swap_lock:
            return dict(self.counters)

    def _bucket(self, n: int) -> int:
        from distributed_tensorflow_tpu.serving.batcher import pow2_bucket

        return pow2_bucket(n, self.max_batch)

    def _apply_fn(self):
        """ONE jitted apply wrapper per engine — jax.jit specializes and
        caches one executable per padded input shape inside it, and the
        power-of-two bucketing bounds how many shapes it ever sees. The
        input buffer is DONATED only when it can alias an output
        (float inputs; an int32 token batch can never alias the float
        logits, and a dead donation just warns per compile)."""
        with self._fn_lock:
            # the fill races the batcher worker against a direct caller
            # (tests/bench); double-checked so two threads can't build
            # two wrappers and split the per-shape executable cache
            fn = self._apply_cache.get("apply")
            if fn is None:
                if self.jit:
                    import jax

                    donate = ((1,) if np.issubdtype(self.input_dtype,
                                                    np.floating) else ())
                    fn = jax.jit(lambda p, x: self.model.apply(p, x),
                                 donate_argnums=donate)
                else:
                    fn = lambda p, x: self.model.apply(p, x)
                self._apply_cache["apply"] = fn
        return fn

    def predict(self, x) -> np.ndarray:
        """Forward one already-stacked batch (B, ...) -> host outputs
        (B, ...): pads the batch dim to its power-of-two bucket, runs
        the bucket's cached executable, slices the padding back off."""
        x = np.asarray(x, dtype=self.input_dtype)
        b = x.shape[0]
        bucket = self._bucket(b)
        # recompile sentry: the padded bucket shape is exactly what the
        # jitted apply specializes on — a churning signature here is
        # the storm the power-of-two bucketing exists to prevent
        resources.note_signature(
            "serve_predict",
            ((bucket,) + tuple(x.shape[1:]), str(x.dtype)))
        if bucket > b:
            pad = np.zeros((bucket - b, *x.shape[1:]), x.dtype)
            xb = np.concatenate([x, pad], axis=0)
        else:
            xb = x
        params, step = self.current()
        reqtrace.note_served_step(step)
        fn = self._apply_fn()
        # request plane: the forward (staging + dispatch + the
        # device->host readback) is the predict route's "prefill"
        # phase, attributed to every request in the current microbatch
        t0 = time.perf_counter()
        if self.jit:
            out = fn(params, self._stage(xb))
        else:
            out = fn(params, xb)
        out = np.asarray(out)[:b]
        reqtrace.note_phase("prefill", time.perf_counter() - t0)
        return out

    def generate(self, prompts, max_new_tokens: int, *,
                 temperature: float = 0.0, seed: int | None = None) -> dict:
        """Autoregressive decode of a (B, P) prompt batch through the
        preallocated KV cache (serving/decode.py) with the current
        params; per-(bucket, P) cached jitted prefill/step fns.

        ``seed=None`` with temperature > 0 draws fresh entropy per call
        — identical prompts must NOT return identical "random" samples;
        pass an explicit seed for reproducible sampling."""
        from distributed_tensorflow_tpu.serving import decode as dec

        prompts = np.asarray(prompts, dtype=np.int32)
        b = prompts.shape[0]
        bucket = max(self._bucket(b), 2)  # decode floor: see decode.py
        resources.note_signature(
            "serve_decode",
            (bucket, int(prompts.shape[1]), int(max_new_tokens)))
        if bucket > b:
            pad = np.repeat(prompts[-1:], bucket - b, axis=0)
            prompts_b = np.concatenate([prompts, pad], axis=0)
        else:
            prompts_b = prompts
        # ONE (prefill, step) wrapper pair per engine: both consume
        # capacity-padded shapes, so neither depends on the prompt
        # length or bucket — jax.jit specializes per input shape inside
        # the single wrapper, and a per-key wrapper would recompile the
        # same executable for every new prompt length
        with self._fn_lock:
            fns = self._decode_cache.get("decode")
            if fns is None:
                fns = (dec.make_prefill(self.model, jit=self.jit),
                       dec.make_decode_step(self.model, jit=self.jit))
                self._decode_cache["decode"] = fns
        params, step = self.current()
        reqtrace.note_served_step(step)
        rng = None
        if temperature > 0.0:
            import os

            import jax

            if seed is None:
                seed = int.from_bytes(os.urandom(4), "little")
            rng = jax.random.PRNGKey(seed)
        out = dec.generate(self.model, params, prompts_b, max_new_tokens,
                           temperature=temperature, rng=rng,
                           prefill_fn=fns[0], step_fn=fns[1])
        return {"tokens": out["tokens"][:b], "logits": out["logits"][:b]}

    # ------------------------------------------------------ hot-reload

    def reload_if_newer(self) -> dict | None:
        """One watch tick: if the directory holds a newer step, restore
        it through the fallback ladder and atomically swap. Returns a
        report dict, or None when there was nothing newer. NEVER raises
        on a corrupt newest set — the ladder walks back and the engine
        keeps serving (a reload must not take down live traffic).

        Serialized: the watcher tick and a ``check_now()`` caller racing
        each other would both restore the same step (twice the restore
        IO under live traffic), and the slower restore could swap an
        OLDER params set over a newer one."""
        with self._reload_lock:
            found = latest_checkpoint(self.logdir)
            if found is None or found[1] <= self.step:
                return None
            path, step = found
            with trace_span("serve_reload", step=step):
                return self._reload(path, step)

    def _reload(self, path: str, step: int) -> dict | None:
        t0 = time.monotonic()
        serving = self.step
        try:
            fault_point("serve_reload", path=path, step=step)
            out = restore_params_with_fallback(self.logdir,
                                               self._template)
        except Exception as e:
            # ladder exhausted (CheckpointCorruptError), injected error,
            # unreadable directory: keep serving what we have
            with self._swap_lock:
                self.counters["reload_failures"] += 1
            print(f"serving reload failed (still serving step "
                  f"{serving}): {type(e).__name__}: {e}")
            return {"swapped": False, "error": str(e), "step": serving}
        ms = (time.monotonic() - t0) * 1e3
        if out is None:
            with self._swap_lock:
                self.counters["reload_failures"] += 1
            return {"swapped": False, "error": "no restorable checkpoint",
                    "step": serving}
        params, rstep, report = out
        if rstep <= serving:
            # the newest set was corrupt; the ladder landed on (at or
            # below) what we already serve — count it, swap nothing
            with self._swap_lock:
                self.counters["last_fallback_depth"] = \
                    report.fallback_depth
                self.counters["reload_fallbacks"] += 1
            print(f"serving reload: newest checkpoint (step {step}) "
                  f"failed verification; ladder landed on step {rstep} "
                  f"— still serving step {serving}")
            return {"swapped": False, "step": rstep,
                    "fallback_depth": report.fallback_depth,
                    "reload_ms": ms}
        placed = self._place(params)
        with self._swap_lock:
            self._params = placed
            self._step = rstep
            self.counters["last_fallback_depth"] = report.fallback_depth
            self.counters["reloads"] += 1
            self.counters["last_reload_ms"] = ms
        print(f"serving hot-reload: now serving step {rstep} "
              f"(restore+place {ms:.1f} ms, fallback depth "
              f"{report.fallback_depth})")
        return {"swapped": True, "step": rstep, "reload_ms": ms,
                "fallback_depth": report.fallback_depth}

    def stats(self) -> dict:
        with self._swap_lock:
            return {"step": self._step, **self.counters}


class CheckpointWatcher:
    """Polls the logdir every ``interval_s`` and hot-swaps through
    ``engine.reload_if_newer`` — TF-Serving's file-system monitor in one
    daemon thread. ``check_now()`` runs one tick synchronously (tests
    and the bench drive it directly; the engine serializes it against a
    concurrent watcher tick).

    The stop/start handoff is explicit: each ``start()`` hands its
    thread a FRESH stop event, so ``start()`` after ``close()`` launches
    a live watcher instead of one that observes the previous run's set
    event and exits immediately (the silently-dead-watcher race dttsan
    SAN004 now proves absent), and a close() racing a slow in-flight
    reload can never be un-stopped by a concurrent restart."""

    def __init__(self, engine: InferenceEngine, interval_s: float = 10.0):
        self.engine = engine
        self.interval_s = float(interval_s)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._stop = threading.Event()
                self._thread = threading.Thread(
                    target=self._loop, args=(self._stop,),
                    name="serve-ckpt-watcher", daemon=True)
                self._thread.start()
        return self

    def check_now(self) -> dict | None:
        return self.engine.reload_if_newer()

    def _loop(self, stop: threading.Event):
        # the event is an ARGUMENT, not read off self: a restart points
        # self._stop at a fresh event for the new thread, and this one
        # keeps honoring the event close() actually set for it
        while not stop.wait(self.interval_s):
            try:
                self.engine.reload_if_newer()
            except Exception as e:  # the watcher must outlive bad ticks
                print(f"checkpoint watcher tick failed: {e}")

    def close(self):
        with self._lock:
            self._stop.set()
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=10)
            if thread.is_alive():
                print("checkpoint watcher still inside a reload after "
                      "10s; abandoning the daemon thread (its stop "
                      "event is set — it exits after the tick)")
