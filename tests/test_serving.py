"""serving/ — checkpoint-to-traffic: batcher semantics, KV-cache decode
bitwise parity, hot-reload under traffic, corrupt-newest fallback,
DP-vs-TP engine parity, flag validation, metrics plumbing."""

import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu import flags
from distributed_tensorflow_tpu.checkpoint import save_checkpoint
from distributed_tensorflow_tpu.models.transformer import TransformerLM
from distributed_tensorflow_tpu.serving import (
    CheckpointWatcher,
    DynamicBatcher,
    InferenceEngine,
    InferenceServer,
    InProcessClient,
    NoCheckpointError,
    RejectedError,
    generate_group_key,
    make_generate_runner,
    make_predict_runner,
    pow2_bucket,
    predict_group_key,
)
from distributed_tensorflow_tpu.serving import decode
from distributed_tensorflow_tpu.training import create_train_state, sgd
from distributed_tensorflow_tpu.utils import faults
from distributed_tensorflow_tpu.utils.metrics import StreamingHistogram

VOCAB, SEQ, DM, HEADS, BLOCKS = 32, 96, 32, 2, 2


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.reset()
    yield
    faults.reset()


def _model(**kw):
    cfg = dict(vocab_size=VOCAB, seq_len=SEQ, d_model=DM,
               num_heads=HEADS, num_blocks=BLOCKS)
    cfg.update(kw)
    return TransformerLM(**cfg)


@pytest.fixture(scope="module")
def lm_ckpt(tmp_path_factory):
    """(logdir, model, state) — one trained-ish LM checkpoint at step 10
    shared by the engine tests."""
    d = str(tmp_path_factory.mktemp("serve-ckpt"))
    model = _model()
    state = create_train_state(model, sgd(0.1), seed=0)
    save_checkpoint(d, state, 10)
    return d, model, state


# --------------------------------------------------------------- batcher


def _echo_runner(payloads, opts_list):
    return [np.asarray(p) * 2 for p in payloads]


def test_batcher_batches_and_completes():
    hist = StreamingHistogram()
    b = DynamicBatcher(_echo_runner, max_batch=4, max_delay_ms=5,
                       queue_depth=16, latency=hist)
    futs = [b.submit(np.full(3, i, np.float32)) for i in range(6)]
    outs = [f.result(5) for f in futs]
    for i, o in enumerate(outs):
        assert np.array_equal(o, np.full(3, 2 * i, np.float32))
    assert b.stats.completed == 6
    assert b.stats.batches >= 2  # max_batch=4 forces at least two
    assert hist.count == 6
    b.close()


def test_batcher_full_queue_rejects_immediately():
    gate = threading.Event()

    def slow(payloads, opts_list):
        gate.wait(10)
        return payloads

    b = DynamicBatcher(slow, max_batch=1, max_delay_ms=0, queue_depth=2,
                       default_timeout_ms=60_000)
    futs = [b.submit(np.zeros(1))]  # taken by the worker, blocks
    time.sleep(0.05)
    futs += [b.submit(np.zeros(1)), b.submit(np.zeros(1))]  # fills queue
    t0 = time.monotonic()
    with pytest.raises(RejectedError, match="queue full"):
        b.submit(np.zeros(1))
    assert time.monotonic() - t0 < 0.5  # immediate, not a hang
    assert b.stats.rejected_full == 1
    gate.set()
    for f in futs:
        f.result(5)
    b.close()


def test_batcher_deadline_expires_queued_request():
    gate = threading.Event()

    def slow(payloads, opts_list):
        gate.wait(10)
        return payloads

    b = DynamicBatcher(slow, max_batch=1, max_delay_ms=0, queue_depth=8)
    first = b.submit(np.zeros(1), timeout_ms=60_000)  # occupies worker
    time.sleep(0.05)
    doomed = b.submit(np.zeros(1), timeout_ms=30)
    with pytest.raises(RejectedError, match="deadline"):
        doomed.result(5)
    assert b.stats.rejected_deadline == 1
    gate.set()
    first.result(5)
    b.close()


def test_batcher_worker_death_fails_pending_no_hang():
    def deadly(payloads, opts_list):
        raise SystemExit("worker killed")

    b = DynamicBatcher(deadly, max_batch=1, max_delay_ms=0,
                       queue_depth=8)
    futs = [b.submit(np.zeros(1)) for _ in range(3)]
    for f in futs:
        with pytest.raises(BaseException):
            f.result(5)  # bounded: errors, never hangs
    time.sleep(0.05)
    with pytest.raises(RejectedError, match="closed"):
        b.submit(np.zeros(1))


def test_batcher_injected_batch_fault_rejects_then_recovers():
    faults.configure("serve_batch:mode=error:times=1")
    b = DynamicBatcher(_echo_runner, max_batch=1, max_delay_ms=0,
                       queue_depth=8)
    bad = b.submit(np.ones(2))
    with pytest.raises(faults.InjectedFault):
        bad.result(5)
    good = b.submit(np.ones(2))
    assert np.array_equal(good.result(5), 2 * np.ones(2))
    assert b.stats.failed == 1 and b.stats.completed == 1
    b.close()


def test_batcher_admit_fault_is_visible_backpressure():
    faults.configure("serve_admit:mode=error:times=1")
    b = DynamicBatcher(_echo_runner, max_batch=1, max_delay_ms=0,
                       queue_depth=8)
    with pytest.raises(RejectedError, match="admission fault"):
        b.submit(np.ones(2))
    assert np.array_equal(b.submit(np.ones(2)).result(5), 2 * np.ones(2))
    b.close()


def test_batcher_groups_do_not_mix():
    seen = []

    def runner(payloads, opts_list):
        seen.append([len(p) for p in payloads])
        return payloads

    b = DynamicBatcher(runner, max_batch=8, max_delay_ms=20,
                       queue_depth=16,
                       group_key=lambda p, o: len(p))
    futs = [b.submit(np.zeros(3)), b.submit(np.zeros(5)),
            b.submit(np.zeros(3))]
    for f in futs:
        f.result(5)
    b.close()
    assert sorted(map(sorted, seen)) == [[3, 3], [5]]


def test_predict_group_key_isolates_mixed_shapes(lm_ckpt):
    """A different-shape request batches alone — it must not np.stack
    into (and 500) a microbatch of well-formed neighbors."""
    d, model, _ = lm_ckpt
    eng = InferenceEngine(model, d, max_batch=4)
    b = DynamicBatcher(make_predict_runner(eng), max_batch=4,
                       max_delay_ms=5, queue_depth=16,
                       group_key=predict_group_key)
    good = [b.submit(np.zeros(SEQ, np.int32)) for _ in range(2)]
    odd = b.submit(np.zeros(SEQ // 2, np.int32))  # wrong length
    for f in good:
        assert f.result(10).shape == (SEQ, VOCAB)
    with pytest.raises(Exception):  # fails alone (model rejects S != seq_len)
        odd.result(10)
    b.close()


def test_pow2_bucket():
    assert [pow2_bucket(n, 8) for n in (1, 2, 3, 5, 8, 9)] == \
        [1, 2, 4, 8, 8, 8]
    with pytest.raises(ValueError):
        pow2_bucket(0, 8)


# ------------------------------------------------------- KV-cache decode


def test_kv_decode_bitwise_equals_full_recompute(lm_ckpt):
    """>= 64 generated tokens: every step's logits bitwise-match the
    full-prefix recompute at the same position (acceptance criterion)."""
    _, model, state = lm_ckpt
    P, N = 8, 64
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, VOCAB, size=(2, P)).astype(np.int32)
    out = decode.generate(model, state.params, prompts, N)
    toks = out["tokens"]
    assert toks.shape == (2, P + N)

    padded = np.zeros((2, SEQ), np.int32)
    padded[:, :P + N] = toks
    full = np.asarray(model.apply(state.params, jnp.asarray(padded)))
    ref = full[:, P - 1:P + N - 1]  # rows that produced tokens P..P+N-1
    assert np.array_equal(ref, out["logits"])  # BITWISE
    assert np.array_equal(ref.argmax(-1), toks[:, P:])


def test_kv_decode_bitwise_batch_one(lm_ckpt):
    """The GEMV-kernel edge case: a single sequence decodes through the
    row-duplicated path and stays bitwise."""
    _, model, state = lm_ckpt
    P, N = 5, 16
    prompts = np.arange(P, dtype=np.int32)[None, :] % VOCAB
    out = decode.generate(model, state.params, prompts, N)
    padded = np.zeros((2, SEQ), np.int32)
    padded[0, :P + N] = out["tokens"][0]
    padded[1] = padded[0]
    full = np.asarray(model.apply(state.params, jnp.asarray(padded)))[:1]
    assert np.array_equal(full[:, P - 1:P + N - 1], out["logits"])


def test_decode_temperature_and_context_guards(lm_ckpt):
    _, model, state = lm_ckpt
    prompts = np.zeros((2, 4), np.int32)
    out = decode.generate(model, state.params, prompts, 3,
                          temperature=0.7, rng=jax.random.PRNGKey(1))
    assert out["tokens"].shape == (2, 7)
    assert (out["tokens"] >= 0).all() and (out["tokens"] < VOCAB).all()
    with pytest.raises(ValueError, match="context window"):
        decode.generate(model, state.params, np.zeros((1, SEQ), np.int32),
                        1)
    with pytest.raises(ValueError, match="seq_axis"):
        decode.check_decodable(_model(seq_axis="model"))
    with pytest.raises(ValueError, match="MoE"):
        decode.check_decodable(_model(moe_experts=4))


# ---------------------------------------------------------------- engine


def test_client_enforces_new_token_defaults_and_cap(lm_ckpt):
    """--serve_max_new_tokens is the omitted-field default AND the cap:
    an over-budget request is rejected loudly, not run."""
    d, model, _ = lm_ckpt
    eng = InferenceEngine(model, d, max_batch=4)
    gb = DynamicBatcher(make_generate_runner(eng), max_batch=4,
                        max_delay_ms=1, queue_depth=8,
                        group_key=generate_group_key)
    client = InProcessClient(generate_batcher=gb,
                             default_max_new_tokens=5,
                             max_new_tokens_cap=5)
    toks = client.generate(np.arange(4, dtype=np.int32))  # omitted -> 5
    assert len(toks) == 4 + 5
    with pytest.raises(ValueError, match="cap"):
        client.generate(np.arange(4, dtype=np.int32), max_new_tokens=64)
    gb.close()


def test_seeded_generate_reproducible_under_concurrency(lm_ckpt):
    """An explicitly-seeded request returns the same tokens whether it
    arrives alone or alongside identical concurrent requests — seeded
    requests batch alone so batch composition cannot change the draw."""
    d, model, _ = lm_ckpt
    eng = InferenceEngine(model, d, max_batch=4)
    gb = DynamicBatcher(make_generate_runner(eng), max_batch=4,
                        max_delay_ms=1, queue_depth=16,
                        default_timeout_ms=60_000,
                        group_key=generate_group_key)
    client = InProcessClient(generate_batcher=gb)
    prompt = np.arange(4, dtype=np.int32)
    futs = [gb.submit(prompt, max_new_tokens=6, temperature=1.0, seed=7)
            for _ in range(3)]
    outs = [np.asarray(f.result(60)) for f in futs]
    solo = np.asarray(client.generate(prompt, max_new_tokens=6,
                                      temperature=1.0, seed=7))
    for o in outs:
        assert np.array_equal(o, solo)
    gb.close()


def test_engine_temperature_draws_fresh_entropy(lm_ckpt):
    """Unseeded sampling must differ call-to-call (identical prompts
    never get identical 'random' completions); an explicit seed is
    reproducible."""
    d, model, _ = lm_ckpt
    eng = InferenceEngine(model, d, max_batch=4)
    prompts = np.arange(4, dtype=np.int32)[None, :] % VOCAB
    outs = [eng.generate(prompts, 12, temperature=1.0)["tokens"].tolist()
            for _ in range(3)]
    assert not (outs[0] == outs[1] == outs[2]), "unseeded sampling froze"
    s1 = eng.generate(prompts, 12, temperature=1.0, seed=7)
    s2 = eng.generate(prompts, 12, temperature=1.0, seed=7)
    assert np.array_equal(s1["tokens"], s2["tokens"])


def test_restore_params_with_fallback_bare_leaf_subtree(tmp_path):
    """The params field being a single bare array still restores through
    the subtree selection (bare-leaf templates flatten to the empty
    path key)."""
    from distributed_tensorflow_tpu.checkpoint.checkpoint import (
        restore_params_with_fallback,
    )

    arr = np.arange(6, dtype=np.float32)
    save_checkpoint(str(tmp_path), {"params": arr, "step": 3}, 5)
    out = restore_params_with_fallback(str(tmp_path),
                                       np.zeros_like(arr))
    assert out is not None
    params, step, _ = out
    assert step == 5 and np.array_equal(np.asarray(params), arr)


def test_engine_requires_checkpoint(tmp_path):
    with pytest.raises(NoCheckpointError):
        InferenceEngine(_model(), str(tmp_path))


def test_engine_predict_buckets_and_pads(lm_ckpt):
    d, model, state = lm_ckpt
    eng = InferenceEngine(model, d, max_batch=8)
    x = np.zeros((3, SEQ), np.int32)
    direct = np.asarray(model.apply(state.params, jnp.asarray(
        np.zeros((4, SEQ), np.int32))))[:3]  # what the padded bucket runs
    out = eng.predict(x)
    assert out.shape == (3, SEQ, VOCAB)
    np.testing.assert_allclose(out, direct, rtol=0, atol=0)
    # bucketing: 3 -> 4 and 5 -> 8 pad to distinct shapes, 2 reuses the
    # size-2 bucket; all slice back to the request size
    assert eng.predict(np.zeros((5, SEQ), np.int32)).shape[0] == 5
    assert eng.predict(np.zeros((2, SEQ), np.int32)).shape[0] == 2


def test_engine_generate_parity_with_library_decode(lm_ckpt):
    d, model, state = lm_ckpt
    eng = InferenceEngine(model, d, max_batch=4)
    prompts = np.arange(6, dtype=np.int32)[None, :] % VOCAB
    lib = decode.generate(model, state.params, prompts, 8)
    served = eng.generate(prompts, 8)
    assert np.array_equal(lib["tokens"], served["tokens"])


def test_engine_dp_tp_parity_same_checkpoint(lm_ckpt):
    """Acceptance: the same checkpoint served DP-replicated and
    TP-sharded answers identically (to float tolerance — TP's psum
    reassociates the contractions)."""
    d, model, _ = lm_ckpt
    from distributed_tensorflow_tpu.parallel import MeshSpec, make_mesh

    mesh = make_mesh(MeshSpec(data=-1, model=2))
    x = np.arange(4 * SEQ, dtype=np.int32).reshape(4, SEQ) % VOCAB
    eng_dp = InferenceEngine(model, d, mesh=mesh, tp=False, max_batch=4)
    eng_tp = InferenceEngine(model, d, mesh=mesh, tp=True, max_batch=4)
    out_dp = eng_dp.predict(x)
    out_tp = eng_tp.predict(x)
    np.testing.assert_allclose(out_dp, out_tp, atol=2e-5, rtol=2e-5)
    g_dp = eng_dp.generate(x[:2, :8], 6)
    g_tp = eng_tp.generate(x[:2, :8], 6)
    assert np.array_equal(g_dp["tokens"], g_tp["tokens"])


def test_hot_reload_swaps_mid_traffic_zero_drops(tmp_path):
    """A newer checkpoint hot-swaps between microbatches while requests
    are in flight: every request answers, outputs flip to the new
    params, nothing drops (acceptance criterion)."""
    d = str(tmp_path)
    model = _model()
    state = create_train_state(model, sgd(0.1), seed=0)
    save_checkpoint(d, state, 10)
    eng = InferenceEngine(model, d, max_batch=4)
    batcher = DynamicBatcher(make_predict_runner(eng), max_batch=4,
                             max_delay_ms=1, queue_depth=64,
                             default_timeout_ms=60_000)
    x = np.zeros(SEQ, np.int32)
    before = batcher.submit(x).result(10)

    stop = threading.Event()
    errors: list = []
    results: list = []

    def traffic():
        while not stop.is_set():
            try:
                results.append(batcher.submit(x).result(10))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

    threads = [threading.Thread(target=traffic, daemon=True)
               for _ in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.1)
    state2 = state._replace(
        params=jax.tree.map(lambda p: p * 1.05, state.params))
    save_checkpoint(d, state2, 20)
    rep = CheckpointWatcher(eng).check_now()
    time.sleep(0.1)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    batcher.close()

    assert rep["swapped"] and rep["step"] == 20
    assert not errors, f"dropped requests during hot-reload: {errors[:3]}"
    after = eng.predict(x[None])[0]
    assert not np.array_equal(before, after)  # the swap took
    assert results, "traffic never ran"


def test_corrupt_newest_reload_rides_fallback_ladder(tmp_path):
    """--fault_spec serve_reload:mode=torn_file tears the newest set at
    reload time: the ladder quarantines it, the engine keeps serving the
    fallback step, in-flight AND subsequent requests all answer
    (acceptance criterion)."""
    d = str(tmp_path)
    model = _model()
    state = create_train_state(model, sgd(0.1), seed=0)
    save_checkpoint(d, state, 10)
    eng = InferenceEngine(model, d, max_batch=4)
    batcher = DynamicBatcher(make_predict_runner(eng), max_batch=4,
                             max_delay_ms=1, queue_depth=64,
                             default_timeout_ms=60_000)
    x = np.zeros(SEQ, np.int32)
    baseline = batcher.submit(x).result(10)

    stop = threading.Event()
    errors: list = []
    served = [0]

    def traffic():
        while not stop.is_set():
            try:
                batcher.submit(x).result(10)
                served[0] += 1
            except Exception as e:  # noqa: BLE001
                errors.append(e)

    threads = [threading.Thread(target=traffic, daemon=True)
               for _ in range(2)]
    for t in threads:
        t.start()
    state2 = state._replace(
        params=jax.tree.map(lambda p: p * 2.0, state.params))
    save_checkpoint(d, state2, 20)
    faults.configure("serve_reload:mode=torn_file")
    rep = eng.reload_if_newer()
    stop.set()
    for t in threads:
        t.join(timeout=10)

    assert rep is not None and not rep["swapped"]
    assert rep["fallback_depth"] >= 1
    assert eng.step == 10  # still serving the verified set
    corrupt = [n for n in os.listdir(d) if ".corrupt" in n]
    assert corrupt, "torn newest set was not quarantined"
    assert not errors, f"dropped requests during corrupt reload: {errors[:3]}"
    # zero dropped: traffic served before, during, and after
    after = batcher.submit(x).result(10)
    assert np.array_equal(baseline, after)
    assert served[0] > 0
    batcher.close()


# ------------------------------------------------- server + HTTP routes


def test_http_server_routes_and_backpressure(lm_ckpt):
    d, model, _ = lm_ckpt
    eng = InferenceEngine(model, d, max_batch=4)
    hist = StreamingHistogram()
    pb = DynamicBatcher(make_predict_runner(eng), max_batch=4,
                        max_delay_ms=1, queue_depth=8, latency=hist)
    gb = DynamicBatcher(make_generate_runner(eng), max_batch=4,
                        max_delay_ms=1, queue_depth=8,
                        group_key=generate_group_key)
    client = InProcessClient(pb, gb)
    srv = InferenceServer(eng, client, port=0).start_background()
    try:
        def post(path, obj):
            req = urllib.request.Request(
                srv.address + path, data=json.dumps(obj).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                return json.loads(r.read())

        health = json.loads(urllib.request.urlopen(
            srv.address + "/healthz", timeout=10).read())
        assert health["ok"] is True and health["step"] == 10
        assert health["params_step"] == 10
        assert health["closed_batchers"] == []

        toks = post("/v1/generate",
                    {"prompt": list(range(8)), "max_new_tokens": 4})
        assert len(toks["tokens"]) == 12

        out = post("/v1/predict",
                   {"inputs": np.zeros(SEQ).tolist()})
        assert np.asarray(out["outputs"]).shape == (SEQ, VOCAB)

        stats = json.loads(urllib.request.urlopen(
            srv.address + "/stats", timeout=10).read())
        assert stats["engine"]["step"] == 10
        assert stats["predict_batcher"]["completed"] >= 1
        assert "latency_ms_p99" in stats["predict_batcher"]

        # backpressure surfaces as HTTP 429 with the reason
        gb.close(drain=False)
        with pytest.raises(urllib.error.HTTPError) as ei:
            post("/v1/generate", {"prompt": [1, 2, 3]})
        assert ei.value.code == 429
    finally:
        srv.close()
        pb.close(drain=False)


# ----------------------------------------------- flags, metrics, profile


@pytest.fixture
def fresh_flags():
    flags.define_reference_flags()
    flags.FLAGS._reset()
    yield
    flags.FLAGS._reset()


@pytest.mark.parametrize("argv,msg", [
    (["--serve_max_batch=0"], "serve_max_batch"),
    (["--serve_max_batch=6"], "power of two"),
    (["--serve_queue_depth=2", "--serve_max_batch=8"], "queue_depth"),
    (["--serve_max_delay_ms=-1"], "serve_max_delay_ms"),
    (["--serve_timeout_ms=0"], "serve_timeout_ms"),
    (["--serve_tp=3", "--num_heads=4"], "num_heads"),
    (["--serve_tp=0"], "serve_tp"),
    (["--serve_max_new_tokens=0"], "serve_max_new_tokens"),
    (["--serve_profile_batches=-1"], "serve_profile_batches"),
])
def test_serving_flag_validators_reject_at_parse(fresh_flags, argv, msg):
    with pytest.raises(ValueError, match=msg):
        flags.FLAGS._parse(argv)


def test_serving_flag_defaults_parse_clean(fresh_flags):
    flags.FLAGS._parse([])
    assert flags.FLAGS.serve_max_batch == 8
    assert flags.FLAGS.serve_port == 8000
    # TP degree dividing heads passes
    flags.FLAGS._reset()
    flags.FLAGS._parse(["--serve_tp=2", "--num_heads=4"])
    assert flags.FLAGS.serve_tp == 2


def test_streaming_histogram_quantiles():
    h = StreamingHistogram()
    for v in range(1, 1001):  # 1..1000 ms uniform
        h.record(float(v))
    assert h.count == 1000
    assert abs(h.quantile(0.5) - 500) < 50   # within bucket resolution
    assert abs(h.quantile(0.99) - 990) < 100
    assert h.quantile(0.5) <= h.quantile(0.9) <= h.quantile(0.99)
    s = h.summary("serve_latency_ms_")
    assert set(s) == {"serve_latency_ms_p50", "serve_latency_ms_p90",
                      "serve_latency_ms_p99", "serve_latency_ms_mean",
                      "serve_latency_ms_count"}
    h.reset()
    assert h.count == 0 and h.quantile(0.5) == 0.0


def test_serving_metrics_land_in_jsonl_sinks(tmp_path, lm_ckpt):
    d, model, _ = lm_ckpt
    from distributed_tensorflow_tpu.serving.server import ServingMetrics
    from distributed_tensorflow_tpu.utils.metrics import MetricsLogger

    eng = InferenceEngine(model, d, max_batch=4)
    logdir = str(tmp_path / "logs")
    logger = MetricsLogger(logdir, job_name="serve",
                           filename="serve_metrics.jsonl")
    metrics = ServingMetrics(logger, eng, emit_every=1)
    hist = StreamingHistogram()
    b = DynamicBatcher(make_predict_runner(eng), max_batch=2,
                       max_delay_ms=1, queue_depth=16, latency=hist,
                       on_batch=metrics.on_batch)
    for _ in range(3):
        b.submit(np.zeros(SEQ, np.int32)).result(10)
    b.close()
    logger.close()
    lines = [json.loads(ln) for ln in
             open(os.path.join(logdir, "serve_metrics.jsonl"))]
    assert lines, "no serving scalars emitted"
    keys = set(lines[-1])
    assert {"serve_queue_depth", "serve_throughput_rps",
            "serve_reloads"} <= keys
    assert any(k.startswith("serve_latency_ms_p99") for k in keys)
    assert any(f.startswith("events.out.tfevents")
               for f in os.listdir(logdir))


@pytest.mark.slow  # compiles a full predict bucket just to fill the
# trace window — the capture machinery itself is covered without it
def test_serve_profile_trace_capture(tmp_path, lm_ckpt):
    d, model, _ = lm_ckpt
    from distributed_tensorflow_tpu.utils.profiling import (
        ServeTraceCapture,
    )

    eng = InferenceEngine(model, d, max_batch=2)
    cap = ServeTraceCapture(str(tmp_path / "trace"), 2)
    assert cap.on_batch() is None
    eng.predict(np.zeros((1, SEQ), np.int32))  # real work in the window
    path = cap.on_batch()
    assert path == str(tmp_path / "trace")
    assert cap.on_batch() is None  # one-shot
    assert os.path.isdir(path) and os.listdir(path)


# --------------------------------------------------- bench serving drill


def test_bench_serving_phase_fields_non_null():
    import bench

    rec = bench.serving_phase()
    assert rec.get("serving_error") is None, rec
    for k in ("serving_p50_ms", "serving_p99_ms",
              "serving_throughput_rps", "serving_reload_blip_ms",
              "serving_reload_fallback_depth"):
        assert rec[k] is not None, (k, rec)
    assert rec["serving_dropped"] == 0
    assert rec["serving_p50_ms"] <= rec["serving_p99_ms"]


@pytest.mark.slow  # runs every host-only bench drill end-to-end (~35 s);
# the per-phase field contracts have their own tier-1 tests
def test_bench_degraded_record_keeps_serving_fields(monkeypatch):
    import bench

    rec = bench.degraded_record("UNAVAILABLE: forced", {}, cpu_smoke=False)
    assert rec["serving_p50_ms"] is not None
    assert rec["serving_reload_blip_ms"] is not None
    assert rec["serving_throughput_rps"] is not None
