"""Efficiency accounting: FLOPs budgets, MFU, and goodput.

After the PR-6 telemetry spine the repo can say where a step's
milliseconds went, but not how much of the HARDWARE they bought. This
module is the accounting layer behind three scalars every training loop
now emits next to ``images_per_sec``:

- ``model_flops_per_sec`` — model FLOPs actually retired per second
  (training FLOPs per example x examples/sec; the Megatron-LM
  "model FLOPs" convention — rematerialization and other implementation
  FLOPs deliberately NOT counted, so the number is comparable across
  implementations).
- ``mfu`` — model FLOPs utilization: ``model_flops_per_sec`` over the
  hardware's peak (Narayanan et al. 2021; Chowdhery et al. 2022's
  refinement is the same ratio with this module's model-FLOPs
  numerator). The headline metric of the large-scale-training
  literature, now a per-window scalar here.
- ``goodput`` — productive fraction of wall time: 1 minus the time
  charged to stalls (restore, checkpoint writes/fetches, display and
  periodic evals, the first-step XLA compile) over the wall time since
  the loop started. ``images_per_sec`` already prices the steady state;
  goodput prices everything AROUND it.

``flops_budget(model, batch)`` follows the ``zero_memory_budget`` dual
pattern: an ANALYTIC per-layer table that works chip-less (the loops and
the degraded bench record use it), plus an optional jitted-lowering
``cost_analysis()`` cross-check where the backend reports FLOPs
(``xla=True``; ``tools/trace_ops.py --flops`` prints both).

Peak FLOP/s resolves in order: ``--mfu_peak_flops`` override, a table of
known TPU chips (by ``device_kind``), else a one-shot cached matmul
calibration on the local backend — so MFU stays meaningful (measured
rate vs measured achievable peak) even on the CPU test mesh.

stdlib-only at import time (jax is imported lazily inside the functions
that need it) so the flags validator and bench's host-only phases can
import this from anywhere, like utils/telemetry.
"""

from __future__ import annotations

import threading
import time

# training FLOPs ~= forward + backward; the backward pass costs ~2x the
# forward (grads wrt both activations and weights) — the standard 3x
# accounting (Kaplan et al. 2020; Megatron-LM's 6ND has the same factor)
TRAIN_FLOPS_MULTIPLIER = 3

# bf16 peak FLOP/s per chip by device_kind substring (public TPU specs).
# Checked in order; first match wins. "v5lite" covers the bare
# "TPU v5 lite" device_kind this repo's flagship chip reports (which
# contains neither "v5e" nor "v5litepod" once normalized).
TPU_PEAK_FLOPS = (
    ("v5p", 459e12),
    ("v5litepod", 197e12),
    ("v5lite", 197e12),
    ("v5e", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)

# matmul calibration (unknown backends, e.g. the CPU test mesh): one
# square f32 matmul timed best-of-reps; achieved FLOP/s stands in for
# peak. Cached per process — the loops must not pay it per run.
CALIBRATE_DIM = 1536
CALIBRATE_REPS = 3

_PEAK_CACHE: dict = {}
_PEAK_LOCK = threading.Lock()


def _conv_flops(kh, kw, cin, cout, hout, wout):
    return 2 * kh * kw * cin * cout * hout * wout


def _dense_flops(m, n):
    return 2 * m * n


def _ceil_div(a, b):
    return -(-a // b)


def _cnn_rows(model) -> list[dict]:
    s = model.image_size
    s2 = _ceil_div(s, 2)
    rows = [
        {"layer": "conv1 5x5", "flops": _conv_flops(5, 5, model.channels, 32, s, s)},
        {"layer": "conv2 5x5", "flops": _conv_flops(5, 5, 32, 64, s2, s2)},
        {"layer": "dense1", "flops": _dense_flops(model.flat_dim, model.hidden_units)},
        {"layer": "logits", "flops": _dense_flops(model.hidden_units, model.num_classes)},
    ]
    return rows


def _mlp_rows(model) -> list[dict]:
    return [
        {"layer": "hidden", "flops": _dense_flops(model.flat_dim, model.hidden_units)},
        {"layer": "logits", "flops": _dense_flops(model.hidden_units, model.num_classes)},
    ]


def _resnet_rows(model) -> list[dict]:
    s = model.image_size
    rows = [{"layer": "stem 3x3",
             "flops": _conv_flops(3, 3, model.channels, model.widths[0], s, s)}]
    cin = model.widths[0]
    size = s
    for si, width in enumerate(model.widths):
        for b in range(model.n):
            stride = 2 if (si > 0 and b == 0) else 1
            if stride == 2:
                size = _ceil_div(size, 2)
            f = (_conv_flops(3, 3, cin, width, size, size)
                 + _conv_flops(3, 3, width, width, size, size))
            if stride != 1 or cin != width:
                f += _conv_flops(1, 1, cin, width, size, size)
            rows.append({"layer": f"stage{si}/block{b}", "flops": f})
            cin = width
    rows.append({"layer": "head",
                 "flops": _dense_flops(model.widths[-1], model.num_classes)})
    return rows


def _transformer_rows(model) -> list[dict]:
    """MiniTransformer / TransformerLM (MoE included): per-EXAMPLE
    forward FLOPs. Attention is the full causal score matrix (2*S^2*d
    each for scores and values — what the dense/blockwise/ring forms
    all compute); a top-1 switch MoE MLP moves each token through
    exactly one expert, so its per-token compute equals the dense MLP
    (capacity-dropped tokens make this a slight over-count, the
    standard convention)."""
    s = model.seq_len
    d = model.d_model
    mlp = model.mlp_dim
    rows = []
    if hasattr(model, "vocab_size"):  # TransformerLM: lookup embed, LM head
        head = {"layer": "lm_head", "flops": s * _dense_flops(d, model.vocab_size)}
    else:  # MiniTransformer: input projection + pooled classifier head
        rows.append({"layer": "embed_proj",
                     "flops": s * _dense_flops(model.token_dim, d)})
        head = {"layer": "cls_head", "flops": _dense_flops(d, model.num_classes)}
    per_block = (
        4 * s * _dense_flops(d, d)        # q, k, v, out projections
        + 2 * (2 * s * s * d)             # scores QK^T + attn*V
        + 2 * s * _dense_flops(d, mlp)    # MLP (or one switch expert) up+down
    )
    for b in range(model.num_blocks):
        rows.append({"layer": f"block{b}", "flops": per_block})
    rows.append(head)
    return rows


def _analytic_rows(model) -> list[dict]:
    name = type(model).__name__
    if name == "DeepCNN":
        return _cnn_rows(model)
    if name == "MLP":
        return _mlp_rows(model)
    if name in ("ResNet", "ResNet20", "ResNet32"):
        return _resnet_rows(model)
    if name in ("MiniTransformer", "TransformerLM"):
        return _transformer_rows(model)
    raise ValueError(
        f"no analytic FLOPs rule for model type {name!r} — efficiency "
        f"accounting knows deep_cnn/mlp/resnet*/transformer/lm")


def xla_cost_flops(model, batch_size: int) -> float | None:
    """The dual pattern's other half: FLOPs per TRAINING step from the
    jitted lowering's ``cost_analysis()`` where the backend reports it
    (None where it doesn't — never an error). Costs a lowering+compile:
    a CLI/bench tool, not a hot-loop call."""
    try:
        import jax
        import jax.numpy as jnp

        if getattr(model, "stateful", False):
            return None  # (params, state) protocol: skip the cross-check
        if hasattr(model, "vocab_size"):  # LM: token batch
            x = jnp.zeros((batch_size, model.seq_len), jnp.int32)
            y = jnp.zeros((batch_size, model.seq_len), jnp.int32)

            def loss_fn(params):
                logits = model.apply(params, x)
                lp = jax.nn.log_softmax(logits.astype(jnp.float32))
                return -jnp.mean(jnp.take_along_axis(lp, y[..., None],
                                                     axis=-1))
        else:
            feat = model.image_size * model.image_size * model.channels
            x = jnp.zeros((batch_size, feat), jnp.float32)
            y = jnp.zeros((batch_size, model.num_classes), jnp.float32)

            def loss_fn(params):
                logits = model.apply(params, x)
                lp = jax.nn.log_softmax(logits.astype(jnp.float32))
                return -jnp.mean(jnp.sum(y * lp, axis=-1))

        params = jax.eval_shape(lambda: model.init(jax.random.PRNGKey(0)))
        params = jax.tree.map(lambda a: jnp.zeros(a.shape, a.dtype), params)
        step = jax.jit(jax.grad(loss_fn))
        cost = step.lower(params).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax: one entry per device
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0)) if cost else 0.0
        return flops if flops > 0 else None
    except Exception:  # noqa: BLE001 — absence of the stat, not an error
        return None


def flops_budget(model, batch_size: int = 1, *, xla: bool = False) -> dict:
    """STATIC per-layer FLOPs budget for one training step of ``model``
    at ``batch_size`` — the ``zero_memory_budget`` dual pattern: the
    analytic table needs no chip and no compute; ``xla=True`` adds the
    jitted-lowering ``cost_analysis()`` total as a cross-check where the
    backend reports it (``xla_flops_per_step``, else None).

    Returns rows of per-example FORWARD FLOPs plus:
    ``fwd_flops_per_example``, ``train_flops_per_example`` (the 3x
    fwd+bwd accounting), ``flops_per_step`` (train x batch), and
    ``source``."""
    batch_size = int(batch_size)
    if batch_size < 1:
        raise ValueError(f"batch_size must be >= 1, got {batch_size}")
    rows = _analytic_rows(model)
    fwd = sum(r["flops"] for r in rows)
    train = TRAIN_FLOPS_MULTIPLIER * fwd
    out = {
        "rows": rows,
        "batch_size": batch_size,
        "fwd_flops_per_example": fwd,
        "train_flops_per_example": train,
        "flops_per_step": train * batch_size,
        "source": "analytic",
        "xla_flops_per_step": None,
    }
    if xla:
        measured = xla_cost_flops(model, batch_size)
        if measured is not None:
            out["xla_flops_per_step"] = measured
            out["source"] = "analytic+xla_cost_analysis"
    return out


def _calibrate_matmul_peak() -> float:
    """Achieved FLOP/s of a square f32 matmul on the default backend —
    the measured-achievable peak that stands in where no spec table
    applies (the CPU test mesh, unknown accelerators)."""
    import jax
    import jax.numpy as jnp

    n = CALIBRATE_DIM
    x = jnp.ones((n, n), jnp.float32)
    f = jax.jit(lambda a: a @ a)
    jax.block_until_ready(f(x))  # compile outside the clock
    best = float("inf")
    for _ in range(CALIBRATE_REPS):
        t0 = time.perf_counter()
        jax.block_until_ready(f(x))
        best = min(best, time.perf_counter() - t0)
    return 2.0 * n ** 3 / best


def peak_flops_per_sec(override: float = 0.0) -> tuple[float, str]:
    """(peak FLOP/s per chip, source). Resolution order: an explicit
    ``override`` (--mfu_peak_flops), the TPU spec table by device_kind,
    else the cached matmul calibration."""
    if override and override > 0:
        return float(override), "flag_override"
    with _PEAK_LOCK:
        if "peak" in _PEAK_CACHE:
            return _PEAK_CACHE["peak"]
        try:
            import jax

            kind = jax.devices()[0].device_kind.lower()
            for tag, peak in TPU_PEAK_FLOPS:
                if tag in kind.replace(" ", "").replace("tpu", ""):
                    _PEAK_CACHE["peak"] = (peak, f"device_table:{tag}")
                    return _PEAK_CACHE["peak"]
            _PEAK_CACHE["peak"] = (_calibrate_matmul_peak(),
                                   "matmul_calibration")
        except Exception as e:  # noqa: BLE001 — accounting never kills a run
            # no backend at all: a conservative 1 GFLOP/s floor keeps the
            # ratio defined (and obviously-wrong enough to investigate)
            _PEAK_CACHE["peak"] = (1e9, f"fallback:{type(e).__name__}")
        return _PEAK_CACHE["peak"]


def _reset_peak_cache() -> None:
    """Testing hook."""
    with _PEAK_LOCK:
        _PEAK_CACHE.clear()


class GoodputMeter:
    """Run-level goodput: productive wall-time fraction.

    ``charge(dt, kind)`` books a stall — restore, checkpoint write or
    boundary fetch, display/periodic eval, the first-step compile —
    against the wall clock running since construction (``reset()``
    restarts it). ``scalars()`` returns the cumulative ratio: goodput
    is a property of the RUN, not of a window (a 30 s restore must keep
    depressing it, not scroll out of a window)."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self._t0 = time.perf_counter()
        self._lost = 0.0
        self._by_kind: dict[str, float] = {}

    def charge(self, dt: float, kind: str = "other") -> None:
        dt = max(0.0, float(dt))
        self._lost += dt
        self._by_kind[kind] = self._by_kind.get(kind, 0.0) + dt

    @property
    def lost_s(self) -> float:
        return self._lost

    def by_kind(self) -> dict[str, float]:
        return dict(self._by_kind)

    def scalars(self) -> dict:
        wall = time.perf_counter() - self._t0
        # resize_s (r15): the elasticity supervisor's drain+reinit+
        # restore downtime is a NAMED stall category — always present
        # (0.0 when no membership change happened) so dashboards and
        # fleet_report can chart it without schema sniffing
        resize = round(self._by_kind.get("resize", 0.0), 4)
        if wall <= 0:
            return {"goodput": 1.0, "goodput_lost_s": 0.0,
                    "resize_s": resize}
        ratio = min(max((wall - self._lost) / wall, 0.0), 1.0)
        return {"goodput": round(ratio, 6),
                "goodput_lost_s": round(self._lost, 4),
                "resize_s": resize}


class EfficiencyMeter:
    """The loops' one-stop efficiency accountant: MFU + model FLOP/s
    from the analytic budget, goodput from explicit stall charges.

    ``scalars(images_per_sec)`` (global examples/sec across chips) is
    emitted at the display cadence next to ``images_per_sec``; costs two
    multiplies and a clock read — hot-path safe."""

    def __init__(self, model, batch_size: int, n_chips: int,
                 peak_override: float = 0.0):
        budget = flops_budget(model, batch_size)
        self.train_flops_per_example = budget["train_flops_per_example"]
        self.flops_per_step = budget["flops_per_step"]
        peak, src = peak_flops_per_sec(peak_override)
        self.peak_flops_total = peak * max(1, int(n_chips))
        self.peak_source = src
        # the goodput wall clock runs from construction and never
        # resets: the loops charge the restore, the compile-carrying
        # first dispatch, and every later stall against it, so the
        # ratio is cumulative over the RUN by construction
        self.goodput = GoodputMeter()

    def charge(self, dt: float, kind: str = "other") -> None:
        self.goodput.charge(dt, kind)

    def scalars(self, images_per_sec: float) -> dict:
        mfs = float(images_per_sec) * self.train_flops_per_example
        out = {
            "model_flops_per_sec": round(mfs, 1),
            "mfu": round(mfs / self.peak_flops_total, 6)
            if self.peak_flops_total > 0 else 0.0,
        }
        out.update(self.goodput.scalars())
        return out


def meter_from_flags(FLAGS, model, batch_size: int,
                     n_chips: int) -> EfficiencyMeter | None:
    """The one flag->feature mapping for ``--mfu`` / ``--mfu_peak_flops``,
    shared by every training loop. None when accounting is off or the
    model has no analytic rule (unknown custom models train fine, just
    without mfu scalars — accounting must never block training)."""
    if not bool(getattr(FLAGS, "mfu", True)):
        return None
    try:
        return EfficiencyMeter(
            model, batch_size, n_chips,
            peak_override=float(getattr(FLAGS, "mfu_peak_flops", 0.0) or 0.0))
    except Exception as e:  # noqa: BLE001 — accounting never kills a run
        print(f"efficiency accounting disabled: {e}")
        return None
