from distributed_tensorflow_tpu.ops.nn import (
    conv2d,
    maxpool2d,
    dense,
    dropout,
    softmax_cross_entropy,
    accuracy,
)

__all__ = [
    "conv2d",
    "maxpool2d",
    "dense",
    "dropout",
    "softmax_cross_entropy",
    "accuracy",
]
