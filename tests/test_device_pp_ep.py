"""Device-resident stepping for pipeline- and expert-parallel training
(training/device_step.make_pp_device_train_step / make_ep_device_train_step)
plus the axis-correct global-norm clip (the advisor's two high-severity
divergence bugs): trajectory equivalence against the host-fed steps given
the same sampled batches, exact-clip trajectories against the single-device
clipped step (replicated leaves bit-identical across the model axis), the
zero-transfer/one-dispatch-per-chunk contract, and the --device_data
--pipeline / --expert_parallel CLI paths the guards used to reject."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.data.device_data import put_device_data
from distributed_tensorflow_tpu.data.lm import LMDataSet
from distributed_tensorflow_tpu.models.transformer import TransformerLM
from distributed_tensorflow_tpu.parallel import MeshSpec, make_mesh
from distributed_tensorflow_tpu.parallel.expert_parallel import (
    ep_clip_transform,
    make_ep_train_step,
    shard_state_ep,
)
from distributed_tensorflow_tpu.parallel.mesh import MODEL_AXIS
from distributed_tensorflow_tpu.parallel.pipeline_parallel import (
    fetch_state_pp,
    make_pp_train_step,
    pp_clip_transform,
    shard_state_pp,
    stage_batch_pp,
)
from distributed_tensorflow_tpu.training import (
    create_train_state,
    get_optimizer,
    make_train_step,
)
from distributed_tensorflow_tpu.training.device_step import (
    _SAMPLE_SALT,
    make_ep_device_train_step,
    make_pp_device_train_step,
)
from distributed_tensorflow_tpu.training.train_state import (
    clip_by_global_norm,
)

KW = dict(vocab_size=16, seq_len=32, d_model=32, num_heads=2, num_blocks=4)
MOE_KW = dict(vocab_size=16, seq_len=32, d_model=32, num_heads=2,
              num_blocks=2, moe_experts=4, moe_capacity=8.0)


def _sampled_global_batch(rng, split, data_ways: int, local_batch: int):
    """Replicate the resident samplers' PRNG math on the host: the split
    is DATA-SHARDED (row-major: shard a holds rows [a*N/D, (a+1)*N/D)),
    each data shard folds (salt, its axis index) on the step rng and
    gathers local rows — the global batch is the shards' rows
    concatenated (stage order of P(DATA_AXIS, None))."""
    x_all = np.asarray(split.images)
    y_all = np.asarray(split.labels)
    local_n = len(x_all) // data_ways
    xs, ys = [], []
    for a in range(data_ways):
        samp = jax.random.fold_in(rng, _SAMPLE_SALT)
        samp = jax.random.fold_in(samp, a)
        idx = np.asarray(jax.random.randint(samp, (local_batch,), 0,
                                            local_n))
        xs.append(x_all[a * local_n + idx])
        ys.append(y_all[a * local_n + idx])
    return np.concatenate(xs), np.concatenate(ys)


def test_pp_device_trajectory_matches_host_fed():
    """Device-sampled chunked PP step == the host-fed PP step given the
    same sampled batches: the input side moved into the program, the
    pipeline math did not change."""
    model = TransformerLM(**KW)
    opt = get_optimizer("sgd", 0.05)
    base = create_train_state(model, opt, seed=0)
    mesh = make_mesh(MeshSpec(data=2, model=4))
    ds = LMDataSet(64, seq_len=32, vocab_size=16, seed=3)
    data = put_device_data(ds, mesh, data_sharded=True)
    B, T = 8, 2

    dev = shard_state_pp(base, mesh)
    dstep = make_pp_device_train_step(model, opt, mesh, B, 4,
                                      keep_prob=1.0, chunk=T,
                                      donate=False)
    dev, m = dstep(dev, data)
    assert np.isfinite(float(m["loss"]))

    host = shard_state_pp(base, mesh)
    hstep = make_pp_train_step(model, opt, mesh, microbatches=4,
                               keep_prob=1.0, donate=False)
    for _ in range(T):
        rng = jax.device_get(host.rng)
        batch = _sampled_global_batch(rng, ds, 2, B // 2)
        host, mh = hstep(host, stage_batch_pp(mesh, batch))

    np.testing.assert_allclose(float(m["loss"]), float(mh["loss"]),
                               rtol=2e-5)
    a_host = fetch_state_pp(host, model)
    a_dev = fetch_state_pp(dev, model)
    assert int(a_dev.step) == T
    for a, b in zip(jax.tree.leaves(a_host.params),
                    jax.tree.leaves(a_dev.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_ep_device_trajectory_matches_host_fed():
    """Device-sampled chunked EP step == the host-fed EP step given the
    same sampled batches (per-shard routing groups identical: each data
    shard routes the same rows in both paths)."""
    model1 = TransformerLM(**MOE_KW)
    modelP = TransformerLM(**MOE_KW, moe_axis=MODEL_AXIS)
    opt = get_optimizer("sgd", 0.05)
    base = create_train_state(model1, opt, seed=0)
    mesh = make_mesh(MeshSpec(data=2, model=4))
    ds = LMDataSet(64, seq_len=32, vocab_size=16, seed=7)
    data = put_device_data(ds, mesh, data_sharded=True)
    B, T = 8, 2

    dev = shard_state_ep(base, mesh)
    dstep = make_ep_device_train_step(modelP, opt, mesh, B, keep_prob=1.0,
                                      chunk=T, donate=False)
    dev, m = dstep(dev, data)
    assert np.isfinite(float(m["loss"]))

    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_tpu.parallel.mesh import put_global

    host = shard_state_ep(base, mesh)
    hstep = make_ep_train_step(modelP, opt, mesh, keep_prob=1.0,
                               donate=False)
    specs = (NamedSharding(mesh, P("data", None)),
             NamedSharding(mesh, P("data", None)))
    for _ in range(T):
        rng = jax.device_get(host.rng)
        x, y = _sampled_global_batch(rng, ds, 2, B // 2)
        host, mh = hstep(host, put_global(specs, (jnp.asarray(x),
                                                  jnp.asarray(y))))

    np.testing.assert_allclose(float(m["loss"]), float(mh["loss"]),
                               rtol=2e-4)
    assert int(jax.device_get(dev.step)) == T
    for a, b in zip(jax.tree.leaves(jax.device_get(host.params)),
                    jax.tree.leaves(jax.device_get(dev.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-5)


# ------------------------------------------- axis-correct clipping (advisor
# high x2: stage/expert-local norms diverged the replicated leaves)


def _assert_replicated_identical(arr):
    shards = [np.asarray(s.data) for s in arr.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)


def test_pp_clip_trajectory_matches_single_device():
    """--clip_norm under PP: the axis-aware transform must reproduce the
    single-device clipped trajectory EXACTLY (same global norm, same
    scale), and the replicated leaves must stay bit-identical across the
    model axis. clip_norm small enough that every step actually clips."""
    model = TransformerLM(**KW)
    opt = get_optimizer("sgd", 0.05)
    base = create_train_state(model, opt, seed=0)
    mesh = make_mesh(MeshSpec(data=2, model=4))

    single = create_train_state(model, opt, seed=0)
    step1 = make_train_step(model, opt, keep_prob=1.0, donate=False,
                            grad_transform=clip_by_global_norm(0.05))
    pp_state = shard_state_pp(base, mesh)
    stepP = make_pp_train_step(model, opt, mesh, microbatches=4,
                               keep_prob=1.0, donate=False,
                               grad_transform=pp_clip_transform(0.05))

    ds = LMDataSet(64, seq_len=32, vocab_size=16, seed=11)
    for _ in range(3):
        b = ds.next_batch(16)
        single, m1 = step1(single, b)
        pp_state, mP = stepP(pp_state, stage_batch_pp(mesh, b))
    np.testing.assert_allclose(float(m1["loss"]), float(mP["loss"]),
                               rtol=2e-5)
    # the advisor-high failure mode: different per-stage scales would
    # desynchronize the replicated copies — they must stay bit-identical
    for leaf in (pp_state.params["tok"], pp_state.params["head"]["w"]):
        _assert_replicated_identical(leaf)
    host = fetch_state_pp(pp_state, model)
    for a, b_ in zip(jax.tree.leaves(single.params),
                     jax.tree.leaves(host.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-5)


def test_ep_clip_trajectory_matches_single_device():
    """--clip_norm under EP: axis-aware clip == single-device clipped MoE
    trajectory (data=1: one routing group, exact standard), replicated
    leaves bit-identical across the expert axis."""
    model1 = TransformerLM(**MOE_KW)
    modelP = TransformerLM(**MOE_KW, moe_axis=MODEL_AXIS)
    opt = get_optimizer("sgd", 0.05)
    base = create_train_state(model1, opt, seed=0)
    mesh = make_mesh(MeshSpec(data=1, model=4), jax.devices()[:4])

    single = create_train_state(model1, opt, seed=0)
    step1 = make_train_step(model1, opt, keep_prob=1.0, donate=False,
                            grad_transform=clip_by_global_norm(0.05))
    ep_state = shard_state_ep(base, mesh)
    stepP = make_ep_train_step(modelP, opt, mesh, keep_prob=1.0,
                               donate=False,
                               grad_transform=ep_clip_transform(0.05))

    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_tpu.parallel.mesh import put_global

    specs = (NamedSharding(mesh, P("data", None)),
             NamedSharding(mesh, P("data", None)))
    ds = LMDataSet(64, seq_len=32, vocab_size=16, seed=17)
    for _ in range(3):
        b = ds.next_batch(8)
        single, m1 = step1(single, b)
        ep_state, mP = stepP(ep_state, put_global(
            specs, (jnp.asarray(b[0]), jnp.asarray(b[1]))))
    np.testing.assert_allclose(float(m1["loss"]), float(mP["loss"]),
                               rtol=2e-4)
    for leaf in (ep_state.params["tok"],
                 ep_state.params["blocks"][0]["moe"]["router"]):
        _assert_replicated_identical(leaf)
    for a, b_ in zip(jax.tree.leaves(single.params),
                     jax.tree.leaves(jax.device_get(ep_state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=3e-4, atol=3e-5)


# ------------------------------------ dispatch amortization + zero transfer


def test_pp_device_one_dispatch_per_chunk_zero_transfer():
    """One compiled call advances ``chunk`` steps, and after warmup the
    dispatch moves NOTHING across the host boundary (the acceptance
    contract: zero per-step host<->device transfer)."""
    model = TransformerLM(**KW)
    opt = get_optimizer("sgd", 0.05)
    mesh = make_mesh(MeshSpec(data=2, model=4))
    ds = LMDataSet(64, seq_len=32, vocab_size=16, seed=5)
    data = put_device_data(ds, mesh, data_sharded=True)
    state = shard_state_pp(create_train_state(model, opt, seed=0), mesh)
    step = make_pp_device_train_step(model, opt, mesh, 8, 4,
                                     keep_prob=1.0, chunk=5)
    state, _ = step(state, data)  # compile + weights upload
    jax.block_until_ready(state.params)
    with jax.transfer_guard("disallow"):
        state, _ = step(state, data)  # steady state: pure dispatch
    assert int(jax.device_get(state.step)) == 10  # 2 calls x chunk 5


def test_ep_device_one_dispatch_per_chunk_zero_transfer():
    model = TransformerLM(**MOE_KW, moe_axis=MODEL_AXIS)
    opt = get_optimizer("sgd", 0.05)
    mesh = make_mesh(MeshSpec(data=2, model=4))
    ds = LMDataSet(64, seq_len=32, vocab_size=16, seed=5)
    data = put_device_data(ds, mesh, data_sharded=True)
    state = shard_state_ep(
        create_train_state(TransformerLM(**MOE_KW), opt, seed=0), mesh)
    step = make_ep_device_train_step(model, opt, mesh, 8, keep_prob=1.0,
                                     chunk=5)
    state, _ = step(state, data)
    jax.block_until_ready(state.params)
    with jax.transfer_guard("disallow"):
        state, _ = step(state, data)
    assert int(jax.device_get(state.step)) == 10


def test_put_device_data_sharded_layout_and_trim():
    """data_sharded staging: example axis split over "data", replicated
    over "model", remainder trimmed to the data ways."""
    mesh = make_mesh(MeshSpec(data=2, model=4))
    ds = LMDataSet(65, seq_len=32, vocab_size=16, seed=1)  # 65 -> trim 64
    data = put_device_data(ds, mesh, data_sharded=True)
    assert data.num_examples == 64
    # each device holds half the examples (data-sharded), full seq axis
    assert data.images.addressable_shards[0].data.shape == (32, 32)
    starts = {s.index[0].start or 0 for s in data.images.addressable_shards}
    assert starts == {0, 32}  # two data rows, each replicated 4x
    # a split smaller than the data axis must refuse loudly, not trim
    # to an empty resident dataset trained on garbage gathers
    with pytest.raises(ValueError, match="cannot shard"):
        put_device_data(LMDataSet(1, seq_len=32, vocab_size=16, seed=1),
                        mesh, data_sharded=True)


# ------------------------------------------------------- loop integration


def _parse(flags, args):
    flags.FLAGS._reset()
    flags.FLAGS._parse(args)
    return flags.FLAGS


def test_device_pp_cli_end_to_end(tmp_path, monkeypatch):
    """--device_data --pipeline through the production CLI (the guard
    this PR removes): trains, clips, checkpoints in the STANDARD layout,
    resumes — and dispatches exactly one compiled call per chunk."""
    import glob

    import distributed_tensorflow_tpu.training.device_step as dsmod
    from distributed_tensorflow_tpu import flags
    from distributed_tensorflow_tpu.training.loop import train

    flags.define_reference_flags()
    calls = {"n": 0}
    orig = dsmod.make_pp_device_train_step

    def counting(*a, **k):
        fn = orig(*a, **k)

        def wrapped(*aa, **kk):
            calls["n"] += 1
            return fn(*aa, **kk)

        return wrapped

    monkeypatch.setattr(dsmod, "make_pp_device_train_step", counting)
    args = [f"--logdir={tmp_path}/logs", f"--data_dir={tmp_path}/none",
            "--dataset=lm", "--model=lm", "--pipeline", "--model_axis=4",
            "--num_blocks=4", "--seq_len=32", "--vocab_size=16",
            "--batch_size=16", "--display_step=3", "--device_data",
            "--device_chunk=3", "--clip_norm=1.0", "--test_eval=false"]
    try:
        res = train(_parse(flags, args + ["--training_iter=6"]),
                    mode="sync")
        assert res.final_step == 6
        assert np.isfinite(res.train_metrics["loss"])
        assert calls["n"] == 2  # 6 steps / chunk 3: one dispatch each
        assert glob.glob(f"{tmp_path}/logs/ckpt-*")
        # resume from the standard-layout checkpoint
        res2 = train(_parse(flags, args + ["--training_iter=9"]),
                     mode="sync")
        assert res2.final_step == 9
    finally:
        flags.FLAGS._reset()


def test_device_ep_cli_end_to_end(tmp_path, monkeypatch):
    """--device_data --expert_parallel through the production CLI (the
    other removed guard): trains, clips, checkpoints, one dispatch per
    chunk."""
    import glob

    import distributed_tensorflow_tpu.training.device_step as dsmod
    from distributed_tensorflow_tpu import flags
    from distributed_tensorflow_tpu.training.loop import train

    flags.define_reference_flags()
    calls = {"n": 0}
    orig = dsmod.make_ep_device_train_step

    def counting(*a, **k):
        fn = orig(*a, **k)

        def wrapped(*aa, **kk):
            calls["n"] += 1
            return fn(*aa, **kk)

        return wrapped

    monkeypatch.setattr(dsmod, "make_ep_device_train_step", counting)
    try:
        res = train(_parse(flags, [
            f"--logdir={tmp_path}/logs", f"--data_dir={tmp_path}/none",
            "--dataset=lm", "--model=lm", "--moe_experts=4",
            "--expert_parallel", "--model_axis=4", "--seq_len=32",
            "--vocab_size=16", "--batch_size=8", "--training_iter=6",
            "--display_step=3", "--device_data", "--device_chunk=3",
            "--clip_norm=1.0", "--test_eval=false"]), mode="sync")
        assert res.final_step == 6
        assert np.isfinite(res.train_metrics["loss"])
        assert calls["n"] == 2
        assert glob.glob(f"{tmp_path}/logs/ckpt-*")
    finally:
        flags.FLAGS._reset()
