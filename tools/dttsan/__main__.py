"""CLI: ``python -m tools.dttsan [--json] [--baseline PATH]
[--threads]``.

Exit status is the tier-1 contract shared with dttlint/dttcheck: 0 when
the tree has no non-baselined findings and no stale suppressions, 1
otherwise. ``--threads`` prints the discovered thread inventory (entry
point, file:line, shared attrs, guarding locks) instead of judging —
the human-readable face of the registry SAN001 enforces; the same table
ships as ``tools/trace_ops.py --threads``, the fifth sibling of
--mem/--flops/--comm/--jaxpr.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# tools/ convention: runnable as a script too
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from tools.dttsan import (  # noqa: E402
    DEFAULT_BASELINE,
    REPO_ROOT,
    run_san,
    threads_table,
)


def print_threads(rows: list[dict], out=sys.stdout) -> None:
    print(f"{'kind':10} {'site':52} {'target':34} shared attrs "
          f"[guarding locks]", file=out)
    print("-" * 118, file=out)
    for r in rows:
        shared = ", ".join(r["shared_attrs"]) or "-"
        locks = ", ".join(r["locks"])
        tail = f"{shared}" + (f"  [{locks}]" if locks else "")
        print(f"{r['kind']:10} {r['site']:52} {r['target']:34} {tail}",
              file=out)
    print(f"\n{len(rows)} concurrent roots "
          f"(threads/timers/handlers/hooks/crash contexts)", file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.dttsan",
        description="dttsan — the static concurrency analyzer "
                    "(passes SAN001-SAN004; see docs/ARCHITECTURE.md "
                    "'Concurrency analysis')")
    ap.add_argument("--json", action="store_true",
                    help="emit one machine-readable JSON object")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="suppression file (default: the checked-in "
                         "tools/dttsan/baseline.json)")
    ap.add_argument("--threads", action="store_true",
                    help="print the discovered thread inventory "
                         "instead of judging")
    ap.add_argument("--registry", default=None,
                    help=argparse.SUPPRESS)  # fixture/test hook
    ap.add_argument("--root", default=REPO_ROOT,
                    help=argparse.SUPPRESS)  # fixture/test hook
    args = ap.parse_args(argv)

    if args.threads:
        rows = threads_table(args.root)
        if args.json:
            print(json.dumps(rows))
        else:
            print_threads(rows)
        return 0

    result = run_san(args.root, args.baseline,
                     registry_path=args.registry)
    if args.json:
        print(json.dumps(result.to_json()))
    else:
        for f in result.findings:
            print(f.format())
        for key in result.stale:
            print(f"{args.baseline}: STALE suppression {key} — the "
                  f"finding no longer exists; delete the entry (the "
                  f"baseline only shrinks)")
        rep = result.report
        print(f"dttsan: {len(result.findings)} finding(s), "
              f"{len(result.baselined)} baselined, "
              f"{len(result.stale)} stale suppression(s) — "
              f"{rep.get('roots_total', 0)} roots, "
              f"{rep.get('locks_total', 0)} locks, "
              f"{rep.get('shared_attrs', 0)} shared attrs across "
              f"{rep.get('classes_total', 0)} classes")
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
