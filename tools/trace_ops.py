"""Aggregate per-op device time from a jax.profiler trace, and print
static pipeline schedules.

The only reliable per-op instrument on tunneled chips (PERF.md): the
trace's device "XLA Ops" lane durations sum to the wall, per-op, where
RPC-latency-polluted microbenchmarks are ~10x wrong. Loads the newest
``*.trace.json.gz`` under a profile dir, selects the XLA Ops thread,
and prints a table: op name, calls, total ms, share, bytes accessed.

``--schedule K M [V]`` instead prints the static pipeline tick table
the --pipeline step compiles for K stages x M microbatches x V virtual
stage groups (parallel/pp_schedule.py — GPipe when V=1, interleaved
when V>1), with the per-stage useful-tick fraction and total scheduled
block-group computations: the masked-tick cost model at a glance, no
chip required.

``--faults`` lists every registered fault-injection point with the
--fault_spec grammar (utils/faults.py) — how the spec strings are
discovered.

Usage: python tools/trace_ops.py /tmp/profile-dir [top_n]
       python tools/trace_ops.py --schedule K M [V]
       python tools/trace_ops.py --faults
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import sys


def load_trace(profile_dir: str) -> dict:
    paths = sorted(
        glob.glob(os.path.join(profile_dir, "**", "*.trace.json.gz"),
                  recursive=True),
        key=os.path.getmtime)
    if not paths:
        raise FileNotFoundError(f"no *.trace.json.gz under {profile_dir}")
    with gzip.open(paths[-1], "rt") as f:
        return json.load(f)


def xla_op_events(trace: dict) -> list[dict]:
    """Complete events on any thread named 'XLA Ops' (the device lane)."""
    tid_names: dict[tuple, str] = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tid_names[(e.get("pid"), e.get("tid"))] = (
                e.get("args", {}).get("name", ""))
    out = []
    for e in trace.get("traceEvents", []):
        if e.get("ph") == "X" and "dur" in e:
            if "XLA Ops" in tid_names.get((e.get("pid"), e.get("tid")), ""):
                out.append(e)
    return out


def aggregate(events: list[dict]) -> list[dict]:
    agg: dict[str, dict] = collections.defaultdict(
        lambda: {"calls": 0, "us": 0.0, "bytes": 0})
    for e in events:
        name = e.get("name", "?")
        a = agg[name]
        a["calls"] += 1
        a["us"] += float(e["dur"])
        args = e.get("args", {})
        try:
            a["bytes"] += int(args.get("bytes_accessed", 0))
        except (TypeError, ValueError):
            pass
    rows = [{"op": k, **v} for k, v in agg.items()]
    rows.sort(key=lambda r: -r["us"])
    return rows


def print_schedule(k_stages: int, microbatches: int,
                   virtual_stages: int = 1) -> None:
    """Print the static (K, M, V) pipeline tick table + schedule cost
    facts — the same builder the compiled step closes over, so what
    prints here IS what runs."""
    import os
    import sys as _sys

    _sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from distributed_tensorflow_tpu.parallel.pp_schedule import (
        build_pp_schedule,
        format_schedule,
    )

    sched = build_pp_schedule(k_stages, microbatches, virtual_stages)
    print(format_schedule(sched))
    per_group = f"num_blocks/{k_stages * virtual_stages}"
    print(f"\nscheduled block-group computations per step: "
          f"{sched.num_ticks * k_stages} x ({per_group} blocks each)")


def print_faults() -> None:
    """List the fault-injection registry (the --fault_spec grammar's
    source of truth — utils/faults.INJECTION_POINTS)."""
    import os
    import sys as _sys

    _sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from distributed_tensorflow_tpu.utils.faults import describe_points

    print(describe_points())


def main(profile_dir: str, top_n: int = 25) -> None:
    rows = aggregate(xla_op_events(load_trace(profile_dir)))
    total_us = sum(r["us"] for r in rows)
    print(f"total device op time: {total_us / 1e3:.2f} ms "
          f"across {sum(r['calls'] for r in rows)} op executions")
    print(f"{'op':<52} {'calls':>6} {'ms':>9} {'share':>6} {'GB':>8}")
    for r in rows[:top_n]:
        print(f"{r['op'][:52]:<52} {r['calls']:>6} {r['us'] / 1e3:>9.2f} "
              f"{r['us'] / total_us:>6.1%} {r['bytes'] / 2**30:>8.2f}")
    rest = rows[top_n:]
    if rest:
        us = sum(r["us"] for r in rest)
        print(f"{'(other ' + str(len(rest)) + ' ops)':<52} "
              f"{sum(r['calls'] for r in rest):>6} {us / 1e3:>9.2f} "
              f"{us / total_us:>6.1%}")


if __name__ == "__main__":
    if sys.argv[1] == "--schedule":
        k, m = int(sys.argv[2]), int(sys.argv[3])
        v = int(sys.argv[4]) if len(sys.argv) > 4 else 1
        print_schedule(k, m, v)
    elif sys.argv[1] == "--faults":
        print_faults()
    else:
        main(sys.argv[1], int(sys.argv[2]) if len(sys.argv) > 2 else 25)
