"""TensorBoard event-file writer: TFRecord framing, masked crc32c, and the
Event/Summary proto subset — verified with an independent parser written
from the wire-format spec (no TF available to cross-check, so the parser
here shares no code with the writer)."""

import glob
import os
import struct

import numpy as np
import pytest

from distributed_tensorflow_tpu.utils.events import EventFileWriter, _crc32c
from distributed_tensorflow_tpu.utils.metrics import MetricsLogger


# ------------------------------------------------------ independent parser

def _read_varint(buf, i):
    out = shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _parse_fields(buf):
    """[(field_number, wire_type, value_bytes_or_int)]"""
    i, out = 0, []
    while i < len(buf):
        key, i = _read_varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, i = _read_varint(buf, i)
        elif wire == 1:
            v, i = buf[i:i + 8], i + 8
        elif wire == 2:
            n, i = _read_varint(buf, i)
            v, i = buf[i:i + n], i + n
        elif wire == 5:
            v, i = buf[i:i + 4], i + 4
        else:
            raise AssertionError(f"unexpected wire type {wire}")
        out.append((field, wire, v))
    return out


def _mask(crc):
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def _read_records(path):
    records = []
    with open(path, "rb") as f:
        data = f.read()
    i = 0
    while i < len(data):
        header = data[i:i + 8]
        (length,) = struct.unpack("<Q", header)
        (hcrc,) = struct.unpack("<I", data[i + 8:i + 12])
        assert hcrc == _mask(_crc32c(header)), "header crc mismatch"
        payload = data[i + 12:i + 12 + length]
        (pcrc,) = struct.unpack("<I", data[i + 12 + length:i + 16 + length])
        assert pcrc == _mask(_crc32c(payload)), "payload crc mismatch"
        records.append(payload)
        i += 16 + length
    return records


def _parse_event(payload):
    event = {"scalars": {}}
    for field, wire, v in _parse_fields(payload):
        if field == 1 and wire == 1:
            event["wall_time"] = struct.unpack("<d", v)[0]
        elif field == 2 and wire == 0:
            event["step"] = v
        elif field == 3 and wire == 2:
            event["file_version"] = v.decode()
        elif field == 5 and wire == 2:
            for f2, w2, value_bytes in _parse_fields(v):
                assert (f2, w2) == (1, 2)
                tag, simple = None, None
                for f3, w3, v3 in _parse_fields(value_bytes):
                    if (f3, w3) == (1, 2):
                        tag = v3.decode()
                    elif (f3, w3) == (2, 5):
                        simple = struct.unpack("<f", v3)[0]
                event["scalars"][tag] = simple
    return event


# ---------------------------------------------------------------- tests

def test_crc32c_known_vectors():
    # published CRC-32C test vectors (RFC 3720 appendix / common suites)
    assert _crc32c(b"") == 0x00000000
    assert _crc32c(b"123456789") == 0xE3069283
    assert _crc32c(b"\x00" * 32) == 0x8A9136AA


def test_event_file_roundtrip(tmp_path):
    w = EventFileWriter(str(tmp_path))
    w.add_scalars(7, {"loss": 1.5, "accuracy": 0.25})
    w.add_scalars(14, {"loss": 0.75})
    w.close()

    records = _read_records(w.path)
    events = [_parse_event(r) for r in records]
    assert events[0]["file_version"] == "brain.Event:2"
    assert events[1]["step"] == 7
    np.testing.assert_allclose(events[1]["scalars"]["loss"], 1.5)
    np.testing.assert_allclose(events[1]["scalars"]["accuracy"], 0.25)
    assert events[2]["step"] == 14
    np.testing.assert_allclose(events[2]["scalars"]["loss"], 0.75)


def test_non_numeric_scalars_skipped(tmp_path):
    w = EventFileWriter(str(tmp_path))
    w.add_scalars(1, {"note": "text", "x": 2.0})
    w.close()
    events = [_parse_event(r) for r in _read_records(w.path)]
    assert events[1]["scalars"] == {"x": 2.0}


def test_real_tensorboard_reads_our_files(tmp_path):
    """The ultimate compatibility check: TensorBoard's own event reader
    (the actual consumer) parses the files this writer produces."""
    ea = pytest.importorskip(
        "tensorboard.backend.event_processing.event_accumulator")

    w = EventFileWriter(str(tmp_path))
    w.add_scalars(3, {"loss": 2.5})
    w.add_scalars(6, {"loss": 1.25, "accuracy": 0.5})
    w.close()

    acc = ea.EventAccumulator(str(tmp_path))
    acc.Reload()
    assert set(acc.Tags()["scalars"]) == {"loss", "accuracy"}
    losses = acc.Scalars("loss")
    assert [(e.step, e.value) for e in losses] == [(3, 2.5), (6, 1.25)]
    accs = acc.Scalars("accuracy")
    assert accs[0].step == 6 and accs[0].value == 0.5


def test_metrics_logger_writes_event_file(tmp_path, capsys):
    logger = MetricsLogger(str(tmp_path), job_name="worker", task_index=0)
    logger.log_display(100, 0.5, 0.9)
    logger.close()
    files = glob.glob(os.path.join(str(tmp_path), "events.out.tfevents.*"))
    assert len(files) == 1
    events = [_parse_event(r) for r in _read_records(files[0])]
    steps = {e.get("step") for e in events[1:]}
    assert steps == {100}
    merged = {}
    for e in events[1:]:
        merged.update(e["scalars"])
    np.testing.assert_allclose(merged["mini_batch_loss"], 0.5)
    np.testing.assert_allclose(merged["training_accuracy"], 0.9)
