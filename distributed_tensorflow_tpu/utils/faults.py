"""Deterministic fault injection for the robustness-claiming layers.

Every layer that promises recovery — checkpoint write/GC, restore/decode,
the Supervisor exit protocol, ``jax.distributed.initialize``,
``prefetch_to_device`` — calls a NAMED injection point at its critical
moment. A ``--fault_spec`` (or the ``DTT_FAULT_SPEC`` env var, which
reaches subprocesses the flag cannot) arms rules against those points, so
every failure mode the recovery code claims to survive is a reproducible
one-liner instead of a hand-rolled monkeypatch:

    --fault_spec ckpt_write:at_step=40:mode=crash
    --fault_spec restore:mode=torn_file
    --fault_spec init:mode=refuse:times=2
    --fault_spec "ckpt_write:mode=bitflip,prefetch:at_count=3:mode=error"

Grammar: comma-separated rules; each rule is ``point[:key=value]...``.
Keys: ``mode`` (what happens — default ``error``), ``at_step``/``at_count``
(fire only when the site reports that step/count), ``after`` (skip the
first N matching hits), ``times`` (fire at most N times; 0 = unlimited;
default 1), ``delay`` (seconds, for ``mode=delay``).

Modes:
  crash      os._exit(FAULT_EXIT_CODE) — a hard machine-crash analog: no
             atexit, no finally, no final checkpoint.
  error      raise InjectedFault at the site (``refuse`` is an alias —
             the connection-refused analog for the ``init`` point).
  torn_file  truncate the file the site names (ctx ``path``) to half its
             bytes — the torn-write signature; execution continues.
  zero_file  truncate that file to zero bytes; execution continues.
  bitflip    flip one bit mid-file; execution continues.
  delay      sleep ``delay`` seconds (default 1.0) — the slow-peer analog
             for the bounded exit-protocol paths.

With no spec configured ``fault_point`` is a no-op (one list check), so
the production paths are byte-identical in behavior to an unarmed build.
This module imports no jax and is safe at any layer.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field

# the registry of every injection point threaded through the tree — the
# one discoverable list (``python tools/trace_ops.py --faults`` prints it).
# A spec naming anything else is rejected at parse time.
INJECTION_POINTS: dict[str, str] = {
    "ckpt_write": "after a checkpoint file lands on disk (monolithic npz "
                  "or one shard), BEFORE the index write and GC "
                  "[ctx: path, step]",
    "ckpt_index": "before the checkpoint index file is atomically "
                  "replaced [ctx: step]",
    "ckpt_gc": "at entry of checkpoint garbage collection [ctx: -]",
    "restore": "before a checkpoint file is read back (both formats) "
               "[ctx: path, step]",
    "exit_agreement": "inside the bounded exit-agreement allgather "
                      "(runs on its run_bounded thread) [ctx: clean]",
    "collective_fetch": "in Supervisor._coordinated_save before the "
                        "state fetch / sharded save [ctx: step]",
    "cancel_gate": "between the exit fetch and the cancel-gated write "
                   "[ctx: step]",
    "init": "before jax.distributed.initialize in "
            "cluster.maybe_initialize_distributed [ctx: attempt]",
    "prefetch": "in prefetch_to_device's staging thread, once per batch "
                "[ctx: count]",
    "serve_admit": "in serving.DynamicBatcher.submit after the admission "
                   "checks pass, before the request enqueues "
                   "[ctx: count]",
    "serve_batch": "in the serving batcher worker after a microbatch is "
                   "assembled, before the engine runs it "
                   "[ctx: count, size]",
    "serve_reload": "in serving.InferenceEngine.reload_if_newer before "
                    "the fallback-ladder restore of a newer checkpoint "
                    "(file modes corrupt that newest set) "
                    "[ctx: path, step]",
    "router_dispatch": "in serving.router before one dispatch attempt "
                       "is sent to the chosen replica (error/refuse "
                       "models a connect-fail the retry path must "
                       "absorb) [ctx: replica, count]",
    "router_health": "in the router's health poller before one "
                     "replica's /healthz+/metrics poll (error models "
                     "an unreachable replica — the breaker's poll-side "
                     "feed) [ctx: replica, count]",
    "router_hedge": "in the router's hedge timer after the latency "
                    "budget expires, before the duplicate dispatch "
                    "launches [ctx: request_id, count]",
    "preempt": "in the elasticity supervisor's boundary poll "
               "(training/elastic.py) — models a spot/preemptible "
               "capacity loss. mode=notice: advance warning, the run "
               "drains to the next checkpoint boundary before the host "
               "departs; mode=immediate: the capacity is gone NOW and "
               "the in-flight step is lost (restore falls back to the "
               "last checkpoint or the sentinel's emergency snapshot). "
               "Keys: host=H (which world member departs; default the "
               "highest-indexed), notice_s=S (the modeled grace "
               "window, recorded in the membership_change span), "
               "rejoin_steps=N (the departed host re-joins N steps "
               "after the resize — the kill-and-re-add chaos shape) "
               "[ctx: step]",
}

MODES = ("crash", "error", "refuse", "torn_file", "zero_file", "bitflip",
         "delay", "notice", "immediate")
_FILE_MODES = ("torn_file", "zero_file", "bitflip")
# preemption modes only make sense on the preempt point (and vice versa:
# a file mode on preempt would ask for a path the poll site cannot name)
_PREEMPT_MODES = ("notice", "immediate")
_PREEMPT_KEYS = ("notice_s", "host", "rejoin_steps")

FAULT_EXIT_CODE = 17  # the injected hard-crash exit status


class InjectedFault(RuntimeError):
    """The error raised by mode=error/refuse — never raised by real code,
    so tests and harnesses can assert the failure was the injected one."""


class Preempted(InjectedFault):
    """Raised by the ``preempt`` point's notice/immediate modes: the
    modeled spot-preemption signal. ONLY the elasticity supervisor's
    boundary poll calls that point, and it catches this exception and
    turns it into a planned membership change (training/elastic.py) —
    an unhandled Preempted means no supervisor was armed, which is
    itself the honest un-elastic behavior (the run dies like a real
    unhandled preemption)."""

    def __init__(self, desc: str, host: int | None = None,
                 notice_s: float = 0.0, immediate: bool = False,
                 rejoin_steps: int = 0, at_step: int | None = None):
        super().__init__(desc)
        self.host = host
        self.notice_s = notice_s
        self.immediate = immediate
        self.rejoin_steps = rejoin_steps
        # the originating rule's identity (host, at_step) lets the
        # elasticity supervisor execute each configured departure at
        # most once per RUN — loop re-entries re-arm the rules, so the
        # fired counter alone cannot carry that guarantee
        self.at_step = at_step


class FaultSpecError(ValueError):
    """A --fault_spec string that doesn't parse (unknown point/mode/key)."""


@dataclass
class FaultRule:
    point: str
    mode: str = "error"
    at_step: int | None = None
    at_count: int | None = None
    after: int = 0
    times: int = 1  # 0 = unlimited
    delay: float = 1.0
    # preempt-point payload (parse rejects these keys elsewhere)
    host: int | None = None
    notice_s: float = 0.0
    rejoin_steps: int = 0
    # mutable runtime counters
    hits: int = field(default=0, compare=False)
    fired: int = field(default=0, compare=False)


_INT_KEYS = ("at_step", "at_count", "after", "times", "host",
             "rejoin_steps")


def parse_fault_spec(spec: str) -> list[FaultRule]:
    """``spec`` -> rules; raises FaultSpecError with the grammar on any
    mistake (this also backs the parse-time flag validator, so a typo
    surfaces at the command line, not mid-run)."""
    rules: list[FaultRule] = []
    for part in (p.strip() for p in (spec or "").split(",")):
        if not part:
            continue
        tokens = part.split(":")
        point = tokens[0].strip()
        if point not in INJECTION_POINTS:
            raise FaultSpecError(
                f"unknown injection point {point!r}; registered points: "
                f"{', '.join(sorted(INJECTION_POINTS))} (see "
                f"tools/trace_ops.py --faults)")
        rule = FaultRule(point=point)
        for tok in tokens[1:]:
            if "=" not in tok:
                raise FaultSpecError(
                    f"bad token {tok!r} in rule {part!r}: expected "
                    f"key=value (grammar: point[:key=value]...)")
            key, val = (s.strip() for s in tok.split("=", 1))
            if key == "mode":
                if val not in MODES:
                    raise FaultSpecError(
                        f"unknown mode {val!r} in rule {part!r}; modes: "
                        f"{', '.join(MODES)}")
                rule.mode = val
            elif key in _INT_KEYS:
                try:
                    setattr(rule, key, int(val))
                except ValueError:
                    raise FaultSpecError(
                        f"{key}={val!r} in rule {part!r}: expected an "
                        f"integer") from None
            elif key in ("delay", "notice_s"):
                try:
                    setattr(rule, key, float(val))
                except ValueError:
                    raise FaultSpecError(
                        f"{key}={val!r} in rule {part!r}: expected "
                        f"seconds") from None
            else:
                raise FaultSpecError(
                    f"unknown key {key!r} in rule {part!r}; keys: mode, "
                    f"{', '.join(_INT_KEYS)}, delay, notice_s")
        _check_preempt_rule(rule, part)
        rules.append(rule)
    return rules


def _check_preempt_rule(rule: FaultRule, part: str) -> None:
    """Cross-field consistency for the preempt point: the preemption
    modes/keys belong to it and to nothing else, and a file mode on it
    would ask for a path the poll site can never name."""
    if rule.point == "preempt":
        if rule.mode in _FILE_MODES:
            raise FaultSpecError(
                f"mode={rule.mode} in rule {part!r}: the preempt poll "
                f"site names no file; preempt modes are "
                f"{', '.join(_PREEMPT_MODES)} (or error/crash/delay)")
        if rule.notice_s < 0:
            raise FaultSpecError(
                f"notice_s={rule.notice_s} in rule {part!r}: the "
                f"preemption grace window must be >= 0 seconds")
        if rule.rejoin_steps < 0:
            raise FaultSpecError(
                f"rejoin_steps={rule.rejoin_steps} in rule {part!r} "
                f"must be >= 0 (0 = the host never re-joins)")
        if rule.host is not None and rule.host < 0:
            raise FaultSpecError(
                f"host={rule.host} in rule {part!r} must be >= 0 (a "
                f"world-member index)")
        return
    if rule.mode in _PREEMPT_MODES:
        raise FaultSpecError(
            f"mode={rule.mode} in rule {part!r} only applies to the "
            f"preempt point (it is the spot-preemption signal)")
    for key in _PREEMPT_KEYS:
        default = FaultRule(point=rule.point)
        if getattr(rule, key) != getattr(default, key):
            raise FaultSpecError(
                f"key {key!r} in rule {part!r} only applies to the "
                f"preempt point (it parameterizes the membership "
                f"change)")


_LOCK = threading.Lock()
_RULES: list[FaultRule] = []
_ENV_CHECKED = False


def configure(spec: str | None) -> list[FaultRule]:
    """Arm (or with None/'' disarm) the injection rules for this process."""
    global _RULES, _ENV_CHECKED
    with _LOCK:
        _RULES = parse_fault_spec(spec) if spec else []
        _ENV_CHECKED = True  # an explicit configure overrides the env var
    return _RULES


def configure_from_flags(FLAGS) -> list[FaultRule]:
    """The one flag->feature mapping for ``--fault_spec``; an empty flag
    falls back to the DTT_FAULT_SPEC env var (the way a test harness arms
    a subprocess it doesn't own the argv of)."""
    spec = getattr(FLAGS, "fault_spec", "") or os.environ.get(
        "DTT_FAULT_SPEC", "")
    return configure(spec)


def reset() -> None:
    """Disarm everything and forget the env check (test isolation)."""
    global _RULES, _ENV_CHECKED
    with _LOCK:
        _RULES = []
        _ENV_CHECKED = False


def active() -> bool:
    return bool(_RULES)


def _ensure_env_rules() -> None:
    """Lazily arm rules from DTT_FAULT_SPEC if no explicit configure ran
    (the one-time env check fault_point performs, factored out so
    ``armed_points`` sees env-armed rules too)."""
    global _ENV_CHECKED
    if _RULES or _ENV_CHECKED:
        return
    with _LOCK:
        if not _ENV_CHECKED:
            _ENV_CHECKED = True
            spec = os.environ.get("DTT_FAULT_SPEC", "")
            if spec:
                _RULES[:] = parse_fault_spec(spec)


def armed_points() -> set:
    """The set of injection-point names with a configured rule (env-var
    rules included) — how the elasticity supervisor auto-arms when a
    ``preempt`` rule exists without an explicit ``--elastic``."""
    _ensure_env_rules()
    return {r.point for r in _RULES}


def _corrupt_file(path: str, mode: str) -> None:
    size = os.path.getsize(path)
    if mode == "zero_file":
        with open(path, "r+b") as f:
            f.truncate(0)
    elif mode == "torn_file":
        with open(path, "r+b") as f:
            f.truncate(max(1, size // 2))
    elif mode == "bitflip":
        with open(path, "r+b") as f:
            f.seek(size // 2)
            b = f.read(1)
            f.seek(size // 2)
            f.write(bytes([(b[0] if b else 0) ^ 0x01]))


def fault_point(name: str, **ctx) -> None:
    """The injection site call. No-op unless a configured rule matches
    ``name`` and the ctx filters; then performs the rule's mode (which may
    not return: crash exits the process, error/refuse raises)."""
    if not _RULES:
        _ensure_env_rules()
        if not _RULES:
            return
    for rule in _RULES:
        if rule.point != name:
            continue
        if rule.at_step is not None and ctx.get("step") != rule.at_step:
            continue
        if rule.at_count is not None and ctx.get("count") != rule.at_count:
            continue
        with _LOCK:
            rule.hits += 1
            if rule.hits <= rule.after:
                continue
            if rule.times and rule.fired >= rule.times:
                continue
            rule.fired += 1
        _fire(rule, name, ctx)


def _fire(rule: FaultRule, name: str, ctx: dict) -> None:
    desc = f"injected fault at {name} (mode={rule.mode}, ctx={ctx})"
    try:
        # flight-recorder hook BEFORE the mode's effect: mode=crash is
        # os._exit — no atexit, no excepthook — so this is the one
        # chance to leave a postmortem (telemetry is stdlib-only; this
        # module stays jax-free)
        from distributed_tensorflow_tpu.utils import telemetry

        telemetry.record_fault(name, rule.mode, ctx)
    except Exception:  # noqa: BLE001 — telemetry never alters fault semantics
        pass
    if rule.mode == "crash":
        print(f"{desc}: hard-exiting {FAULT_EXIT_CODE}", flush=True)
        os._exit(FAULT_EXIT_CODE)
    if rule.mode in _PREEMPT_MODES:
        raise Preempted(desc, host=rule.host, notice_s=rule.notice_s,
                        immediate=(rule.mode == "immediate"),
                        rejoin_steps=rule.rejoin_steps,
                        at_step=rule.at_step)
    if rule.mode in ("error", "refuse"):
        raise InjectedFault(desc)
    if rule.mode == "delay":
        print(f"{desc}: sleeping {rule.delay}s", flush=True)
        time.sleep(rule.delay)
        return
    if rule.mode in _FILE_MODES:
        path = ctx.get("path")
        if not path:
            raise InjectedFault(
                f"{desc}: mode {rule.mode!r} needs a file but injection "
                f"point {name!r} reports no path")
        _corrupt_file(path, rule.mode)
        print(f"{desc}: corrupted {path}", flush=True)
        return
    raise AssertionError(f"unhandled fault mode {rule.mode!r}")


def describe_points() -> str:
    """Human-readable registry (tools/trace_ops.py --faults)."""
    lines = ["registered fault-injection points "
             "(--fault_spec point[:key=value]...[,rule...]):", ""]
    width = max(len(n) for n in INJECTION_POINTS)
    for pname in sorted(INJECTION_POINTS):
        lines.append(f"  {pname:<{width}}  {INJECTION_POINTS[pname]}")
    lines += [
        "",
        f"modes: {', '.join(MODES)} (notice/immediate: preempt only)",
        "keys:  mode, at_step, at_count, after, times (0=unlimited), "
        "delay, host, notice_s, rejoin_steps (last three: preempt only)",
        "examples:",
        "  --fault_spec ckpt_write:at_step=40:mode=crash",
        "  --fault_spec restore:mode=torn_file",
        "  --fault_spec init:mode=refuse:times=2",
        "  --fault_spec preempt:at_step=60:mode=notice:notice_s=30:host=3",
        "  --fault_spec preempt:mode=immediate:host=2:rejoin_steps=40",
        "  DTT_FAULT_SPEC=prefetch:at_count=3:mode=error  (env var form "
        "for subprocesses)",
    ]
    return "\n".join(lines)
