"""DTT007 conforming fixture: structure checks, static-arg dispatch
and jnp-native control flow are all legal in traced bodies."""

import functools

import jax
import jax.numpy as jnp
from jax import lax


def make_step(xs, augment_fn=None):
    def body(carry, x):
        if augment_fn is not None:  # closure structure, not a value
            x = augment_fn(x)
        if x.shape[0] > 1:  # static shape
            x = x[:1]
        carry = carry + jnp.where(x[0] > 0, 1, 0)  # traced branch, in-program
        return carry, x

    return lax.scan(body, 0, xs)


@functools.partial(jax.jit, static_argnames=("interpret",))
def apply(a, interpret=False):
    if interpret:  # static arg: config dispatch, re-traced per value
        return a
    return a * 2
