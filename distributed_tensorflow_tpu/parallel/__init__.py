from distributed_tensorflow_tpu.parallel.mesh import (
    MeshSpec,
    make_mesh,
    batch_sharding,
    replicated_sharding,
)
from distributed_tensorflow_tpu.parallel.data_parallel import (
    make_dp_train_step,
    shard_batch,
)
from distributed_tensorflow_tpu.parallel.tensor_parallel import (
    make_tp_train_step,
    shard_state_tp,
)
from distributed_tensorflow_tpu.parallel.zero import (
    fetch_state_zero,
    make_zero_train_step,
    shard_state_zero,
    zero_clip_transform,
    zero_memory_budget,
)

__all__ = [
    "fetch_state_zero",
    "make_zero_train_step",
    "shard_state_zero",
    "zero_clip_transform",
    "zero_memory_budget",
    "MeshSpec",
    "make_mesh",
    "batch_sharding",
    "replicated_sharding",
    "make_dp_train_step",
    "shard_batch",
    "make_tp_train_step",
    "shard_state_tp",
]
