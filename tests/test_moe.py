"""Switch MoE (ops/moe.py) + expert parallelism
(parallel/expert_parallel.py): routing/capacity semantics, the aux
loss, and the EP trajectory == the IDENTICAL MoE model on one device —
the only exactness standard a sparse layer has (there is no dense
twin)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.data.lm import LMDataSet
from distributed_tensorflow_tpu.models.transformer import TransformerLM
from distributed_tensorflow_tpu.ops.moe import moe_capacity, switch_moe
from distributed_tensorflow_tpu.parallel import MeshSpec, make_mesh
from distributed_tensorflow_tpu.parallel.expert_parallel import (
    make_ep_eval_step,
    make_ep_train_step,
    shard_state_ep,
)
from distributed_tensorflow_tpu.parallel.mesh import MODEL_AXIS
from distributed_tensorflow_tpu.training import (
    create_train_state,
    get_optimizer,
    make_train_step,
)

MOE_KW = dict(vocab_size=16, seq_len=32, d_model=32, num_heads=2,
              num_blocks=2, moe_experts=4)


def _moe_params(key, d=8, e=4, m=16):
    k = iter(jax.random.split(key, 5))
    return {
        "router": jax.random.normal(next(k), (d, e)) * 0.3,
        "w1": jax.random.normal(next(k), (e, d, m)) * 0.3,
        "b1": jnp.zeros((e, m)),
        "w2": jax.random.normal(next(k), (e, m, d)) * 0.3,
        "b2": jnp.zeros((e, d)),
    }


def test_capacity_math():
    assert moe_capacity(64, 4, 1.0) == 16
    assert moe_capacity(64, 4, 1.25) == 20
    assert moe_capacity(3, 8, 1.0) == 1  # floor of one slot


def test_switch_moe_routes_to_argmax_expert():
    """With generous capacity, each token's output must equal
    gate * MLP_{argmax expert}(token) — the top-1 semantics."""
    params = _moe_params(jax.random.PRNGKey(0))
    h = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 8))
    y, aux = switch_moe(h, params, capacity_factor=8.0)
    hf = h.reshape(-1, 8)
    logits = hf @ params["router"]
    probs = jax.nn.softmax(logits, -1)
    e = jnp.argmax(probs, -1)
    gate = jnp.max(probs, -1)
    for t in range(hf.shape[0]):
        ei = int(e[t])
        ref = jax.nn.relu(hf[t] @ params["w1"][ei] + params["b1"][ei])
        ref = (ref @ params["w2"][ei] + params["b2"][ei]) * gate[t]
        np.testing.assert_allclose(np.asarray(y.reshape(-1, 8)[t]),
                                   np.asarray(ref), rtol=1e-5, atol=1e-5)
    assert float(aux["dropped_frac"]) == 0.0
    assert np.isfinite(float(aux["lb_loss"]))


def test_switch_moe_capacity_drops_overflow():
    """Tokens past an expert's capacity contribute zero output (the
    residual stream carries them) and the dropped fraction reports."""
    params = _moe_params(jax.random.PRNGKey(0))
    # route EVERY token to one expert: all-positive tokens against a
    # hard-biased router column (h @ router must win for expert 2
    # regardless of draw, so keep h positive)
    params["router"] = jnp.zeros((8, 4)).at[:, 2].set(100.0)
    h = jnp.abs(jax.random.normal(jax.random.PRNGKey(1), (1, 16, 8))) + 0.1
    y, aux = switch_moe(h, params, capacity_factor=1.0)
    # capacity = ceil(16/4) = 4 -> 12 of 16 dropped
    assert float(aux["dropped_frac"]) == pytest.approx(0.75)
    flat = np.asarray(y.reshape(16, 8))
    assert np.count_nonzero(np.abs(flat).sum(-1) > 1e-12) == 4


def test_switch_moe_capacity_keeps_first_arrivals():
    """Queue positions are FIRST-COME-FIRST-SERVED and integer-exact
    (int32 cumsum — the f32 path lost integer exactness past 2^24
    tokens/shard): with every token routed to one expert at capacity 4,
    exactly the first 4 tokens in arrival order survive."""
    params = _moe_params(jax.random.PRNGKey(0))
    params["router"] = jnp.zeros((8, 4)).at[:, 2].set(100.0)
    h = jnp.abs(jax.random.normal(jax.random.PRNGKey(1), (1, 16, 8))) + 0.1
    y, aux = switch_moe(h, params, capacity_factor=1.0)
    flat = np.asarray(y.reshape(16, 8))
    nonzero = np.abs(flat).sum(-1) > 1e-12
    np.testing.assert_array_equal(
        nonzero, np.arange(16) < 4)  # first 4 arrivals, nothing else


def test_moe_lm_trains_and_aux_loss_flows():
    """A MoE TransformerLM trains through the STANDARD step machinery
    (the loss hook adds the aux term in train mode only) and the lb
    metric reports near its uniform-routing floor of 1.0."""
    model = TransformerLM(**MOE_KW)
    opt = get_optimizer("adam", 3e-3)
    state = create_train_state(model, opt, seed=0)
    step = make_train_step(model, opt, keep_prob=1.0)
    ds = LMDataSet(32, seq_len=32, vocab_size=16, seed=0)
    first = None
    for _ in range(20):
        state, m = step(state, ds.next_batch(8))
        if first is None:
            first = float(m["loss"])
    assert float(m["loss"]) < first
    assert float(m["moe_lb"]) >= 0.99  # >= 1.0 up to fp noise


# NOTE on exactness scope: capacity queues and the load-balance term
# are computed per ROUTING GROUP (= one data shard's tokens) — standard
# Switch semantics, so batch grouping changes which overflow tokens
# drop and the lb statistics. The exact-equality tests therefore use
# data=1 (one group, aux on) and a no-drop capacity; the DP composition
# is pinned separately with the aux coefficient zeroed.


def test_ep_trajectory_matches_single_device():
    """The EP standard: experts sharded 4 ways over (data=1, model=4)
    == the identical MoE model on one device, trajectories to fp
    tolerance (routing identical, psum-combine exact, the 1/P-seed
    gradient accounting correct, aux loss included)."""
    kw = dict(MOE_KW, moe_capacity=8.0)
    model1 = TransformerLM(**kw)
    modelP = TransformerLM(**kw, moe_axis=MODEL_AXIS)
    opt = get_optimizer("sgd", 0.05)
    base = create_train_state(model1, opt, seed=0)
    mesh = make_mesh(MeshSpec(data=1, model=4), jax.devices()[:4])

    single = create_train_state(model1, opt, seed=0)
    step1 = make_train_step(model1, opt, keep_prob=1.0, donate=False)
    ep_state = shard_state_ep(base, mesh)
    stepP = make_ep_train_step(modelP, opt, mesh, keep_prob=1.0,
                               donate=False)

    from distributed_tensorflow_tpu.parallel.expert_parallel import (
        ep_state_specs,
    )
    from distributed_tensorflow_tpu.parallel.mesh import put_global
    from jax.sharding import NamedSharding, PartitionSpec as P

    def stage(b):
        return put_global(
            (NamedSharding(mesh, P("data", None)),
             NamedSharding(mesh, P("data", None))),
            (jnp.asarray(b[0]), jnp.asarray(b[1])))

    ds = LMDataSet(64, seq_len=32, vocab_size=16, seed=17)
    # ONE step pinned TIGHT: at identical params the routing is
    # identical, so any gradient-accounting error (e.g. the P-scaled
    # psum-transpose seeds) shows as a 4x grad error here. Later steps
    # cannot be pinned tightly — top-1 argmax amplifies f32
    # summation-order ulps into discrete routing flips at decision
    # boundaries (inherent to sparse routing, not an EP defect).
    for _ in range(3):
        b = ds.next_batch(8)
        single, m1 = step1(single, b)
        ep_state, mP = stepP(ep_state, stage(b))
    np.testing.assert_allclose(float(m1["loss"]), float(mP["loss"]),
                               rtol=2e-4)
    np.testing.assert_allclose(float(m1["moe_lb"]), float(mP["moe_lb"]),
                               rtol=2e-4)
    for a, b_ in zip(jax.tree.leaves(single.params),
                     jax.tree.leaves(jax.device_get(ep_state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=3e-4, atol=3e-5)
    # the experts really shard: leading E axis 4 -> 1 per device
    w1 = ep_state.params["blocks"][0]["moe"]["w1"]
    assert w1.addressable_shards[0].data.shape[0] == 1

    ev = make_ep_eval_step(modelP, mesh)
    b = ds.next_batch(8)
    staged = put_global(
        (NamedSharding(mesh, P("data", None)),
         NamedSharding(mesh, P("data", None))),
        (jnp.asarray(b[0]), jnp.asarray(b[1])))
    m = ev(ep_state.params, staged)
    assert np.isfinite(float(m["loss"]))


def test_moe_guards():
    with pytest.raises(ValueError, match="needs moe_experts"):
        TransformerLM(vocab_size=16, seq_len=32, d_model=32, num_heads=2,
                      moe_axis=MODEL_AXIS)
    model = TransformerLM(**MOE_KW, moe_axis=MODEL_AXIS)
    opt = get_optimizer("sgd", 0.05)
    mesh3 = make_mesh(MeshSpec(data=1, model=8))
    with pytest.raises(ValueError, match="must divide"):
        make_ep_train_step(model, opt, mesh3)  # 4 experts over 8 ways


def test_ep_composes_with_dp():
    """EP x DP over (data=2, model=4): the per-group routing semantics
    make exact equality vs single-device hold when the aux coefficient
    is zero and capacity never drops (each data shard is its own
    routing group — the documented Switch grouping)."""
    kw = dict(MOE_KW, moe_capacity=8.0, moe_aux=0.0)
    model1 = TransformerLM(**kw)
    modelP = TransformerLM(**kw, moe_axis=MODEL_AXIS)
    opt = get_optimizer("sgd", 0.05)
    base = create_train_state(model1, opt, seed=0)
    mesh = make_mesh(MeshSpec(data=2, model=4))

    from distributed_tensorflow_tpu.parallel.mesh import put_global
    from distributed_tensorflow_tpu.training.train_state import (
        compute_grads,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    ep_state = shard_state_ep(base, mesh)
    stepP = make_ep_train_step(modelP, opt, mesh, keep_prob=1.0,
                               donate=False)
    # manual reference: the DP semantics with per-shard routing groups —
    # grads averaged over the two half-batches (each routed alone)
    from distributed_tensorflow_tpu.training.train_state import (
        apply_updates,
    )

    state = create_train_state(model1, opt, seed=0)
    ds = LMDataSet(64, seq_len=32, vocab_size=16, seed=19)
    for _ in range(2):
        x, y = ds.next_batch(8)
        halves = [(x[:4], y[:4]), (x[4:], y[4:])]
        gs = []
        for hb in halves:
            g, m, _ = compute_grads(model1, state.params, hb,
                                    keep_prob=1.0, rng=None,
                                    model_state=())
            gs.append(g)
        g = jax.tree.map(lambda a, b: (a + b) / 2, *gs)
        updates, opt_state = opt.update(g, state.opt_state, state.params,
                                        state.step)
        state = state._replace(
            params=apply_updates(state.params, updates),
            opt_state=opt_state, step=state.step + 1)
        staged = put_global(
            (NamedSharding(mesh, P("data", None)),
             NamedSharding(mesh, P("data", None))),
            (jnp.asarray(x), jnp.asarray(y)))
        ep_state, mP = stepP(ep_state, staged)
    for a, b_ in zip(jax.tree.leaves(state.params),
                     jax.tree.leaves(jax.device_get(ep_state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=3e-4, atol=3e-5)


def test_expert_parallel_cli_end_to_end(tmp_path):
    """--expert_parallel through the production CLI: trains,
    checkpoints, resumes."""
    import glob

    from distributed_tensorflow_tpu import flags
    from distributed_tensorflow_tpu.training.loop import train

    flags.define_reference_flags()
    try:
        flags.FLAGS._reset()
        flags.FLAGS._parse([
            f"--logdir={tmp_path}/logs", f"--data_dir={tmp_path}/none",
            "--dataset=lm", "--model=lm", "--moe_experts=4",
            "--expert_parallel", "--model_axis=4", "--seq_len=32",
            "--vocab_size=16", "--batch_size=8", "--training_iter=6",
            "--display_step=3", "--test_eval=false",
        ])
        res = train(flags.FLAGS, mode="sync")
        assert res.final_step == 6
        assert np.isfinite(res.train_metrics["loss"])
        assert glob.glob(f"{tmp_path}/logs/ckpt-*")
        flags.FLAGS._reset()
        flags.FLAGS._parse([
            f"--logdir={tmp_path}/logs", f"--data_dir={tmp_path}/none",
            "--dataset=lm", "--model=lm", "--moe_experts=4",
            "--expert_parallel", "--model_axis=4", "--seq_len=32",
            "--vocab_size=16", "--batch_size=8", "--training_iter=9",
            "--display_step=3", "--test_eval=false",
        ])
        assert train(flags.FLAGS, mode="sync").final_step == 9
    finally:
        flags.FLAGS._reset()


def test_expert_parallel_cli_rejections(tmp_path):
    from distributed_tensorflow_tpu import flags
    from distributed_tensorflow_tpu.training.loop import train

    flags.define_reference_flags()

    def parse(*extra):
        flags.FLAGS._reset()
        flags.FLAGS._parse([
            f"--logdir={tmp_path}/l", f"--data_dir={tmp_path}/n",
            "--dataset=lm", "--model=lm", "--seq_len=32",
            "--vocab_size=16", "--batch_size=8", "--training_iter=2",
            *extra,
        ])
        return flags.FLAGS

    try:
        with pytest.raises(ValueError, match="shards MoE experts"):
            train(parse("--expert_parallel", "--model_axis=4"),
                  mode="sync")
        with pytest.raises(ValueError, match="pick one"):
            train(parse("--expert_parallel", "--moe_experts=4",
                        "--model_axis=4", "--seq_parallel"), mode="sync")
        with pytest.raises(ValueError, match="shards nothing"):
            train(parse("--expert_parallel", "--moe_experts=4"),
                  mode="sync")
    finally:
        flags.FLAGS._reset()


def test_moe_excluded_from_sp_and_pp():
    """MoE + the other model-axis strategies fail LOUDLY (not with a
    KeyError mid-trace): SP twin construction and the PP builder both
    reject MoE params up front."""
    from distributed_tensorflow_tpu.parallel.pipeline_parallel import (
        make_pp_train_step,
    )

    model = TransformerLM(**MOE_KW)
    opt = get_optimizer("sgd", 0.05)
    mesh = make_mesh(MeshSpec(data=2, model=4))
    with pytest.raises(ValueError, match="not wired for MoE"):
        make_pp_train_step(model, opt, mesh, microbatches=2)

    from distributed_tensorflow_tpu import flags
    from distributed_tensorflow_tpu.training.loop import train
    import tempfile

    flags.define_reference_flags()
    with tempfile.TemporaryDirectory() as d:
        try:
            flags.FLAGS._reset()
            flags.FLAGS._parse([
                f"--logdir={d}/l", f"--data_dir={d}/n", "--dataset=lm",
                "--model=lm", "--moe_experts=4", "--seq_parallel",
                "--model_axis=4", "--seq_len=32", "--vocab_size=16",
                "--batch_size=8", "--training_iter=2",
            ])
            with pytest.raises(ValueError, match="not supported"):
                train(flags.FLAGS, mode="sync")
        finally:
            flags.FLAGS._reset()
