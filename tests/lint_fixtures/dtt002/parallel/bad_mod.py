"""DTT002 violating fixture: a parallel/ module with a collective but
no *_comm_rows pricing builder."""

from jax import lax

from distributed_tensorflow_tpu.parallel.mesh import MODEL_AXIS


def ring(x, perm):
    return lax.ppermute(x, MODEL_AXIS, perm)
