"""Checkpoint inspection CLI — the ``inspect_checkpoint`` counterpart of
TF's Saver tooling, for this build's npz pytree checkpoints.

    python -m distributed_tensorflow_tpu.checkpoint.inspect --logdir /tmp/train_logs
    python -m distributed_tensorflow_tpu.checkpoint.inspect --path ckpt-1000.npz --key params/weights/wd1

Lists every stored array (path key, shape, dtype — bf16-tagged entries
decoded), the global step, and the total parameter count; ``--key`` also
prints one array's statistics. Read-only; works on checkpoints from every
mode (full TrainState layouts and ps-mode params-only layouts alike).
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from distributed_tensorflow_tpu.checkpoint.checkpoint import latest_checkpoint
from distributed_tensorflow_tpu.utils.pytree import _BF16_TAG


def load_entries(path: str) -> tuple[dict[str, np.ndarray], set[str]]:
    """({clean_key: array}, undecoded_keys) with bf16-tagged entries decoded
    to float32 (a lossless widening — npz stores them as uint16 views).
    ``undecoded_keys`` names bf16-tagged entries left as raw uint16 views
    because ml_dtypes was unavailable — their values are NOT interpretable
    as numbers. Reads both the monolithic npz and the sharded format
    (any shard file of a complete set reassembles the whole state)."""
    from distributed_tensorflow_tpu.checkpoint.checkpoint import load_flat

    try:
        import ml_dtypes

        bf16 = np.dtype(ml_dtypes.bfloat16)
    except ImportError:  # pragma: no cover — ml_dtypes ships with jax
        bf16 = None
    out = {}
    undecoded = set()
    for k, arr in load_flat(path).items():
        if k.startswith(_BF16_TAG):
            k = k[len(_BF16_TAG):]
            if bf16 is not None:
                arr = arr.view(bf16).astype(np.float32)
            else:
                undecoded.add(k)
        out[k] = arr
    return out, undecoded


def describe(path: str, key: str | None = None, out=None) -> int:
    out = out if out is not None else sys.stdout  # bind at call time
    entries, undecoded = load_entries(path)
    step = entries.get("step")
    print(f"checkpoint: {path}", file=out)
    if step is not None:
        print(f"global step: {int(np.asarray(step))}", file=out)
    total = 0
    for k in sorted(entries):
        if k == "step":
            continue
        a = entries[k]
        total += a.size
        dtype = "bfloat16 (raw bits; no ml_dtypes)" if k in undecoded else a.dtype
        print(f"  {k}  shape={tuple(a.shape)}  dtype={dtype}", file=out)
    print(f"total elements (excl. step): {total:,}", file=out)
    if key is not None:
        if key not in entries:
            print(f"error: no array {key!r} in checkpoint "
                  f"(keys: {sorted(entries)[:8]}...)", file=sys.stderr)
            return 2
        if key in undecoded:
            # the stored array is a raw uint16 view of bf16 bits; stats on
            # it would be meaningless — refuse rather than mislead
            print(f"error: {key!r} is stored as bf16 and ml_dtypes is not "
                  f"available to decode it; install ml_dtypes to print "
                  f"statistics", file=sys.stderr)
            return 2
        a = np.asarray(entries[key], np.float64)
        print(f"{key}: min={a.min():.6g} max={a.max():.6g} "
              f"mean={a.mean():.6g} std={a.std():.6g}", file=out)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Inspect a distributed_tensorflow_tpu checkpoint")
    p.add_argument("--logdir", help="checkpoint directory (inspects the "
                   "latest checkpoint, like restore does)")
    p.add_argument("--path", help="a specific ckpt-N.npz file")
    p.add_argument("--key", help="also print statistics of this array")
    args = p.parse_args(argv)
    if bool(args.logdir) == bool(args.path):
        p.error("exactly one of --logdir / --path is required")
    path = args.path
    if args.logdir:
        found = latest_checkpoint(args.logdir)
        if found is None:
            print(f"no checkpoint found in {args.logdir}", file=sys.stderr)
            return 1
        path = found[0]
    return describe(path, args.key)


if __name__ == "__main__":
    sys.exit(main())
