"""The request plane (r19, serving/reqtrace.py): request-id round-trip
over HTTP and in-process clients, waterfall completeness for every
disposition, phase sums vs wall time, tail attribution, the SLO ledger
and its /healthz burn-rate 503, the req_report CLI, loadgen columns,
and flag validation."""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from distributed_tensorflow_tpu import flags
from distributed_tensorflow_tpu.checkpoint import save_checkpoint
from distributed_tensorflow_tpu.models.transformer import TransformerLM
from distributed_tensorflow_tpu.serving import (
    CheckpointWatcher,
    DynamicBatcher,
    InferenceEngine,
    InferenceServer,
    InProcessClient,
    RejectedError,
    generate_group_key,
    make_generate_runner,
    make_predict_runner,
    predict_group_key,
)
from distributed_tensorflow_tpu.serving import reqtrace
from distributed_tensorflow_tpu.training import create_train_state, sgd
from distributed_tensorflow_tpu.utils import faults, telemetry

VOCAB, SEQ, DM, HEADS, BLOCKS = 32, 96, 32, 2, 2


class _HostModel:
    """Minimal host model (no jax): logits = x @ w + b."""

    @staticmethod
    def apply(params, x):
        return np.asarray(x) @ params["w"] + params["b"]


@pytest.fixture(autouse=True)
def _clean_plane_and_faults():
    """Every test starts with no plane, no faults, a quiet tracer ring,
    and leaves none behind (the plane is process-global like the
    telemetry spine)."""
    faults.reset()
    prev = reqtrace.get_plane()
    tracer = telemetry.get_tracer()
    prev_enabled = tracer.enabled
    yield
    faults.reset()
    reqtrace._PLANE = prev
    tracer.enabled = prev_enabled
    telemetry.configure(logdir=None, enabled=prev_enabled)


@pytest.fixture
def plane():
    """An armed request plane with a generous SLO."""
    return reqtrace.configure(enabled=True, slo_p99_ms=60_000.0)


def _host_engine(tmpdir) -> tuple:
    rng = np.random.default_rng(0)
    params = {"w": rng.standard_normal((64, 16)).astype(np.float32),
              "b": np.zeros(16, np.float32)}
    save_checkpoint(str(tmpdir), {"params": params}, 10)
    eng = InferenceEngine(_HostModel(), str(tmpdir), jit=False,
                          params_template=params, max_batch=8)
    return eng, params


def _predict_batcher(eng, **kw):
    cfg = dict(max_batch=8, max_delay_ms=1.0, queue_depth=64,
               group_key=predict_group_key, name="predict")
    cfg.update(kw)
    return DynamicBatcher(make_predict_runner(eng), **cfg)


@pytest.fixture(scope="module")
def lm_ckpt(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("reqtrace-lm"))
    model = TransformerLM(vocab_size=VOCAB, seq_len=SEQ, d_model=DM,
                          num_heads=HEADS, num_blocks=BLOCKS)
    state = create_train_state(model, sgd(0.1), seed=0)
    save_checkpoint(d, state, 10)
    return d, model, state


# ------------------------------------------------------ id round-trip


def test_inprocess_id_minted_and_echoed(tmp_path, plane):
    eng, _ = _host_engine(tmp_path)
    b = _predict_batcher(eng)
    client = InProcessClient(predict_batcher=b)
    x = np.zeros(64, np.float32)
    _out, meta = client.predict_ex(x)
    assert meta["request_id"].startswith("req-")
    assert meta["disposition"] == "ok"
    # a client-supplied id round-trips verbatim
    _out, meta2 = client.predict_ex(x, request_id="req-client-0042")
    assert meta2["request_id"] == "req-client-0042"
    assert plane.audit[-1]["request_id"] == "req-client-0042"
    b.close()


def test_plain_predict_api_unchanged(tmp_path, plane):
    """The non-_ex surface keeps returning the bare result."""
    eng, params = _host_engine(tmp_path)
    b = _predict_batcher(eng)
    client = InProcessClient(predict_batcher=b)
    x = np.ones(64, np.float32)
    out = client.predict(x)
    np.testing.assert_allclose(out, x @ params["w"] + params["b"],
                               rtol=1e-6)
    b.close()


def test_http_id_echo_and_phase_block(lm_ckpt, plane):
    d, model, _ = lm_ckpt
    eng = InferenceEngine(model, d, max_batch=4)
    pb = _predict_batcher(eng, max_batch=4)
    gb = DynamicBatcher(make_generate_runner(eng), max_batch=4,
                        max_delay_ms=1, queue_depth=8,
                        group_key=generate_group_key, name="generate")
    client = InProcessClient(pb, gb)
    srv = InferenceServer(eng, client, port=0).start_background()
    try:
        def post(path, obj):
            req = urllib.request.Request(
                srv.address + path, data=json.dumps(obj).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                return json.loads(r.read())

        # client-supplied id echoes; server-minted id is returned
        out = post("/v1/predict", {"inputs": np.zeros(SEQ).tolist(),
                                   "request_id": "req-http-7"})
        assert out["request_id"] == "req-http-7"
        assert out["disposition"] == "ok"
        assert set(out["phases_ms"]) >= {"admit", "queue_wait",
                                         "batch_assembly", "prefill",
                                         "respond"}
        out = post("/v1/generate", {"prompt": list(range(8)),
                                    "max_new_tokens": 4})
        assert out["request_id"].startswith("req-")
        assert out["phases_ms"]["decode"] >= 0
        # backpressure carries the id too
        gb.close(drain=False)
        with pytest.raises(urllib.error.HTTPError) as ei:
            post("/v1/generate", {"prompt": [1, 2, 3],
                                  "request_id": "req-rej-1"})
        assert ei.value.code == 429
        body = json.loads(ei.value.read())
        assert body["request_id"] == "req-rej-1"
    finally:
        srv.close()
        pb.close(drain=False)


# ------------------------------------------- waterfalls + dispositions


def test_ok_waterfall_complete_and_sums_to_wall(tmp_path, plane):
    eng, _ = _host_engine(tmp_path)
    b = _predict_batcher(eng)
    client = InProcessClient(predict_batcher=b)
    for _ in range(8):
        client.predict_ex(np.zeros(64, np.float32))
    b.close()
    assert len(plane.audit) == 8
    for s in plane.audit:
        assert s["disposition"] == "ok"
        assert set(s["phases_ms"]) >= {"admit", "queue_wait",
                                       "batch_assembly", "prefill",
                                       "respond"}
        # exhaustive phases: the sum IS the wall time (rounding only)
        assert sum(s["phases_ms"].values()) == pytest.approx(
            s["total_ms"], abs=0.05)


def test_rejected_full_and_closed_get_dispositions(tmp_path, plane):
    gate = threading.Event()

    def slow(payloads, opts_list):
        gate.wait(10)
        return payloads

    b = DynamicBatcher(slow, max_batch=1, max_delay_ms=0, queue_depth=2,
                       default_timeout_ms=60_000, name="predict")
    futs = [b.submit(np.zeros(1))]
    time.sleep(0.05)
    futs += [b.submit(np.zeros(1)), b.submit(np.zeros(1))]
    with pytest.raises(RejectedError) as ei:
        b.submit(np.zeros(1))
    assert ei.value.request_id.startswith("req-")
    rec = plane.audit[-1]
    assert rec["disposition"] == "rejected_full"
    assert "queue full" in rec["reason"]
    assert rec["request_id"] == ei.value.request_id
    gate.set()
    for f in futs:
        f.result(5)
    b.close()
    with pytest.raises(RejectedError):
        b.submit(np.zeros(1))
    assert plane.audit[-1]["disposition"] == "rejected_closed"


def test_failed_disposition_on_batch_error(tmp_path, plane):
    faults.configure("serve_batch:mode=error:times=1")
    eng, _ = _host_engine(tmp_path)
    b = _predict_batcher(eng)
    bad = b.submit(np.zeros(64, np.float32))
    with pytest.raises(faults.InjectedFault):
        bad.result(5)
    assert bad.meta["disposition"] == "failed"
    assert "InjectedFault" in bad.meta["reason"]
    rec = plane.audit[-1]
    assert rec["disposition"] == "failed"
    assert rec["request_id"] == bad.request_id
    b.close()


def test_expired_reconstructable_from_span_file_alone(tmp_path, plane):
    """The bugfix acceptance: a deadline-expired request leaves enough
    in spans-*.jsonl that its story — id, disposition, reason, how long
    it queued — reconstructs WITHOUT the server process."""
    logdir = str(tmp_path / "logs")
    telemetry.configure(logdir=logdir, host="serve-0", enabled=True)
    gate = threading.Event()

    def slow(payloads, opts_list):
        gate.wait(10)
        return payloads

    b = DynamicBatcher(slow, max_batch=1, max_delay_ms=0, queue_depth=8,
                       name="predict")
    first = b.submit(np.zeros(1), timeout_ms=60_000)
    time.sleep(0.05)
    doomed = b.submit(np.zeros(1), timeout_ms=30)
    with pytest.raises(RejectedError, match="deadline"):
        doomed.result(5)
    gate.set()
    first.result(5)
    b.close()
    telemetry.get_tracer().flush()

    path = os.path.join(logdir, "spans-serve-0.jsonl")
    recs = [json.loads(ln) for ln in open(path)]
    mine = [r for r in recs
            if r.get("request_id") == doomed.request_id]
    done = [r for r in mine if r["name"] == "req:done"]
    assert done and done[0]["disposition"] == "expired"
    assert "deadline" in done[0]["reason"]
    waits = [r for r in mine if r["name"] == "req:queue_wait"]
    assert waits and waits[0]["dur_s"] * 1e3 >= 25  # queued ~30ms
    # and the offline tool agrees, from the file alone
    from tools import req_report

    reqs = req_report.collect_requests(
        req_report.load_records(path))
    rq = reqs[doomed.request_id]
    assert rq["disposition"] == "expired"
    assert not req_report.incomplete_requests(
        {doomed.request_id: rq})


def test_inflight_timeout_carries_request_id(tmp_path, plane):
    """A request that times out CLIENT-side while still running keeps
    its id on the TimeoutError — the 504 is joinable to the audit
    record the request will eventually land in."""
    gate = threading.Event()

    def slow(payloads, opts_list):
        gate.wait(10)
        return payloads

    b = DynamicBatcher(slow, max_batch=1, max_delay_ms=0, queue_depth=8,
                       default_timeout_ms=60_000, name="predict")
    client = InProcessClient(predict_batcher=b)
    with pytest.raises(TimeoutError) as ei:
        client.predict_ex(np.zeros(1), wait_s=0.05,
                          request_id="req-slow-1")
    assert ei.value.request_id == "req-slow-1"
    gate.set()
    b.close()


def test_generate_decode_phase_and_ticks(lm_ckpt, plane):
    d, model, _ = lm_ckpt
    eng = InferenceEngine(model, d, max_batch=4)
    gb = DynamicBatcher(make_generate_runner(eng), max_batch=4,
                        max_delay_ms=1, queue_depth=8,
                        group_key=generate_group_key, name="generate")
    client = InProcessClient(generate_batcher=gb)
    toks, meta = client.generate_ex(np.arange(8, dtype=np.int32),
                                    max_new_tokens=6)
    assert len(toks) == 8 + 6
    assert meta["phases_ms"]["prefill"] > 0
    assert "decode" in meta["phases_ms"]
    rec = plane.audit[-1]
    assert rec["decode_ticks"] == 6
    assert rec["bucket"] == 8  # prompt-length shape bucket
    gb.close()


def test_seeded_generate_keeps_coherent_timeline(lm_ckpt, plane):
    """A seeded request batches alone (unique group) — its timeline
    must still be complete and its tokens still reproducible."""
    d, model, _ = lm_ckpt
    eng = InferenceEngine(model, d, max_batch=4)
    gb = DynamicBatcher(make_generate_runner(eng), max_batch=4,
                        max_delay_ms=1, queue_depth=16,
                        default_timeout_ms=60_000,
                        group_key=generate_group_key, name="generate")
    client = InProcessClient(generate_batcher=gb)
    prompt = np.arange(4, dtype=np.int32)
    t1, m1 = client.generate_ex(prompt, max_new_tokens=5,
                                temperature=1.0, seed=7)
    t2, m2 = client.generate_ex(prompt, max_new_tokens=5,
                                temperature=1.0, seed=7)
    assert np.array_equal(t1, t2)
    assert m1["request_id"] != m2["request_id"]
    for m in (m1, m2):
        assert m["disposition"] == "ok"
        assert set(m["phases_ms"]) >= {"admit", "queue_wait",
                                       "batch_assembly", "prefill",
                                       "decode", "respond"}
    gb.close()


def test_hot_reload_requests_keep_coherent_timelines(tmp_path, plane):
    """Timelines stay complete across a mid-traffic hot-swap: every
    request in the audit ring is 'ok' with exhaustive phases."""
    d = str(tmp_path)
    model = TransformerLM(vocab_size=VOCAB, seq_len=SEQ, d_model=DM,
                          num_heads=HEADS, num_blocks=BLOCKS)
    state = create_train_state(model, sgd(0.1), seed=0)
    save_checkpoint(d, state, 10)
    eng = InferenceEngine(model, d, max_batch=4)
    b = _predict_batcher(eng, max_batch=4, default_timeout_ms=60_000)
    client = InProcessClient(predict_batcher=b)
    x = np.zeros(SEQ, np.int32)
    stop = threading.Event()
    errors: list = []

    def traffic():
        while not stop.is_set():
            try:
                client.predict_ex(x)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

    threads = [threading.Thread(target=traffic, daemon=True)
               for _ in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.1)
    state2 = state._replace(
        params=jax.tree.map(lambda p: p * 1.05, state.params))
    save_checkpoint(d, state2, 20)
    rep = CheckpointWatcher(eng).check_now()
    time.sleep(0.1)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    b.close()
    assert rep["swapped"]
    assert not errors
    audit = list(plane.audit)
    assert audit
    for s in audit:
        assert s["disposition"] == "ok"
        assert sum(s["phases_ms"].values()) == pytest.approx(
            s["total_ms"], abs=0.05)


# ------------------------------------------- tail + SLO + /healthz 503


def test_injected_delay_dominates_the_right_phase(tmp_path, plane):
    """The acceptance drill shape: an injected serve_batch delay (fires
    between take and execution) must surface as a batch_assembly-
    dominated tail, live AND offline."""
    logdir = str(tmp_path / "logs")
    telemetry.configure(logdir=logdir, host="serve-0", enabled=True)
    eng, _ = _host_engine(tmp_path)
    faults.configure("serve_batch:mode=delay:delay=0.05:times=100")
    b = _predict_batcher(eng, default_timeout_ms=60_000)
    client = InProcessClient(predict_batcher=b)
    for _ in range(4):
        client.predict_ex(np.zeros(64, np.float32))
    b.close()
    telemetry.get_tracer().flush()
    tail = plane.tail_report()
    entry = tail["routes"]["predict"]["64"]
    assert entry["p99_dominant_phase"] == "batch_assembly"
    assert entry["phases"]["batch_assembly"]["p50_ms"] >= 40
    for ex in tail["exemplars"]:
        assert ex["dominant_phase"] == "batch_assembly"
    # offline agreement from the span file alone
    from tools import req_report

    reqs = req_report.collect_requests(req_report.load_records(
        os.path.join(logdir, "spans-serve-0.jsonl")))
    off = req_report.tail_attribution(reqs)
    assert off["predict"]["64"]["p99_dominant_phase"] == \
        "batch_assembly"


def test_slo_ledger_trips_and_healthz_503(tmp_path):
    plane = reqtrace.configure(enabled=True, slo_p99_ms=0.0001,
                               slo_target_pct=99.0)
    eng, _ = _host_engine(tmp_path)
    b = _predict_batcher(eng)
    client = InProcessClient(predict_batcher=b)
    srv = InferenceServer(eng, client, port=0).start_background()
    try:
        for _ in range(12):  # >= MIN_WINDOW_COUNT, all non-compliant
            client.predict_ex(np.zeros(64, np.float32))
        rep = plane.slo_report()
        assert rep["compliant_pct"] == 0.0
        assert rep["budget_remaining_pct"] == 0.0
        assert rep["burn_rate_fast"] >= rep["fast_burn_threshold"]
        assert rep["fast_burn_breach"] is True
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(srv.address + "/healthz", timeout=10)
        assert ei.value.code == 503
        body = json.loads(ei.value.read())
        assert body["ok"] is False and body["slo_fast_burn"] is True
        m = json.loads(urllib.request.urlopen(
            srv.address + "/metrics", timeout=10).read())
        assert m["slo"]["fast_burn_breach"] is True
        assert m["tail"]["exemplars"], "tail exemplars missing"
    finally:
        srv.close()
        b.close(drain=False)


def test_slo_compliant_path_stays_healthy(tmp_path):
    plane = reqtrace.configure(enabled=True, slo_p99_ms=60_000.0)
    eng, _ = _host_engine(tmp_path)
    b = _predict_batcher(eng)
    client = InProcessClient(predict_batcher=b)
    srv = InferenceServer(eng, client, port=0).start_background()
    try:
        for _ in range(12):
            client.predict_ex(np.zeros(64, np.float32))
        rep = plane.slo_report()
        assert rep["compliant_pct"] == 100.0
        assert rep["budget_remaining_pct"] == 100.0
        assert rep["fast_burn_breach"] is False
        h = json.loads(urllib.request.urlopen(
            srv.address + "/healthz", timeout=10).read())
        assert h["ok"] is True and h["slo_fast_burn"] is False
    finally:
        srv.close()
        b.close(drain=False)


def test_serving_metrics_cadence_emits_slo_scalars(tmp_path):
    from distributed_tensorflow_tpu.serving.server import ServingMetrics
    from distributed_tensorflow_tpu.utils.metrics import MetricsLogger

    reqtrace.configure(enabled=True, slo_p99_ms=60_000.0)
    eng, _ = _host_engine(tmp_path)
    logdir = str(tmp_path / "logs")
    logger = MetricsLogger(logdir, job_name="serve",
                           filename="serve_metrics.jsonl")
    metrics = ServingMetrics(logger, eng, emit_every=1)
    b = _predict_batcher(eng, on_batch=metrics.on_batch)
    client = InProcessClient(predict_batcher=b)
    for _ in range(3):
        client.predict_ex(np.zeros(64, np.float32))
    b.close()
    logger.close()
    lines = [json.loads(ln) for ln in
             open(os.path.join(logdir, "serve_metrics.jsonl"))]
    keys = set(lines[-1])
    assert {"serve_slo_compliant_pct", "serve_slo_budget_remaining_pct",
            "serve_slo_burn_rate_fast"} <= keys
    assert lines[-1]["serve_slo_compliant_pct"] == 100.0


def test_metrics_blocks_none_when_plane_unconfigured(tmp_path):
    reqtrace.configure(enabled=False)
    eng, _ = _host_engine(tmp_path)
    b = _predict_batcher(eng)
    client = InProcessClient(predict_batcher=b)
    srv = InferenceServer(eng, client, port=0).start_background()
    try:
        m = srv.metrics()
        assert m["tail"] is None and m["slo"] is None
        assert srv.healthz()["slo_fast_burn"] is False
    finally:
        srv.close()
        b.close(drain=False)


# -------------------------------------------------------- req_report CLI


def _drive_some_traffic(tmp_path, logdir, n=20):
    telemetry.configure(logdir=logdir, host="serve-0", enabled=True)
    reqtrace.configure(enabled=True, slo_p99_ms=60_000.0)
    eng, _ = _host_engine(tmp_path)
    b = _predict_batcher(eng, default_timeout_ms=60_000)
    client = InProcessClient(predict_batcher=b)
    for _ in range(n):
        client.predict_ex(np.zeros(64, np.float32))
    b.close()
    telemetry.get_tracer().flush()


def test_req_report_json_chrome_and_exit_codes(tmp_path, capsys):
    from tools import req_report

    logdir = str(tmp_path / "logs")
    _drive_some_traffic(tmp_path, logdir, n=20)

    # exit 0 + json report
    rc = req_report.main([logdir, "--json", "--slo_p99_ms", "60000"])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["requests_total"] == 20
    assert rep["by_disposition"] == {"ok": 20}
    assert rep["complete_pct"] == 100.0
    assert rep["tail"]["predict"]["64"]["phases"]["queue_wait"]["p99_ms"] >= 0
    assert rep["slo"]["compliant_pct"] == 100.0
    assert rep["exemplars"][0]["request_id"].startswith("req-")

    # chrome export: one track (thread_name metadata event) per request
    out = str(tmp_path / "req.json")
    rc = req_report.main([logdir, "--chrome", out])
    assert rc == 0
    trace = json.load(open(out))
    names = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    assert len(names) == 20
    assert len({e["tid"] for e in names}) == 20
    capsys.readouterr()

    # human report + single-request waterfall
    rc = req_report.main([logdir])
    assert rc == 0
    text = capsys.readouterr().out
    assert "tail attribution" in text and "worst exemplars" in text
    rid = rep["exemplars"][0]["request_id"]
    rc = req_report.main([logdir, "--request", rid])
    assert rc == 0
    assert rid in capsys.readouterr().out

    # exit 2: no request records
    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    with open(os.path.join(empty, "spans-serve-0.jsonl"), "w") as f:
        f.write(json.dumps({"name": "serve_batch", "ts": 1.0,
                            "dur_s": 0.1}) + "\n")
    assert req_report.main([empty]) == 2
    assert req_report.main([str(tmp_path / "nowhere")]) == 2

    # exit 1: an incomplete timeline (phase spans but no req:done)
    broken = str(tmp_path / "broken")
    os.makedirs(broken)
    with open(os.path.join(broken, "spans-serve-0.jsonl"), "w") as f:
        f.write(json.dumps({"name": "req:admit", "ts": 1.0,
                            "dur_s": 0.001,
                            "request_id": "req-x-1"}) + "\n")
    assert req_report.main([broken, "--json"]) == 1


# ----------------------------------------------------- loadgen columns


def test_loadgen_closed_loop_phase_and_slo_columns(tmp_path, plane):
    from tools.serve_loadgen import run_closed_loop

    eng, _ = _host_engine(tmp_path)
    b = _predict_batcher(eng, default_timeout_ms=60_000)
    client = InProcessClient(predict_batcher=b)
    x = np.zeros(64, np.float32)

    def request():
        _out, meta = client.predict_ex(x)
        return meta

    rep = run_closed_loop(request, n_requests=30, concurrency=3,
                          slo_p99_ms=60_000.0)
    b.close()
    assert rep["ok"] == 30 and rep["errors"] == 0
    assert rep["id_echo_failures"] == 0
    assert rep["slo_compliant_pct"] == 100.0
    assert set(rep["phase_ms"]) >= {"admit", "queue_wait",
                                    "batch_assembly", "prefill",
                                    "respond"}
    for cols in rep["phase_ms"].values():
        assert cols["p50"] <= cols["p99"]


def test_loadgen_http_echo_verified(lm_ckpt, plane):
    from tools.serve_loadgen import http_request_fn, run_closed_loop

    d, model, _ = lm_ckpt
    eng = InferenceEngine(model, d, max_batch=4)
    pb = _predict_batcher(eng, max_batch=4, default_timeout_ms=60_000)
    client = InProcessClient(predict_batcher=pb)
    srv = InferenceServer(eng, client, port=0).start_background()
    try:
        fn = http_request_fn(srv.address, "predict", input_dim=SEQ)
        rep = run_closed_loop(fn, n_requests=12, concurrency=2,
                              slo_p99_ms=60_000.0)
        assert rep["ok"] == 12 and rep["id_echo_failures"] == 0
        assert rep["phase_ms"] is not None
    finally:
        srv.close()
        pb.close(drain=False)


# ---------------------------------------------------- flags + telemetry


@pytest.fixture
def fresh_flags():
    flags.define_reference_flags()
    flags.FLAGS._reset()
    yield
    flags.FLAGS._reset()


@pytest.mark.parametrize("argv,msg", [
    (["--slo_p99_ms=-1"], "slo_p99_ms"),
    (["--slo_target_pct=40"], "slo_target_pct"),
    (["--slo_target_pct=100.5"], "slo_target_pct"),
    (["--slo_target_pct=95"], "slo_target_pct without"),
    (["--reqtrace_ring=4"], "reqtrace_ring"),
    (["--reqtrace_exemplars=0"], "reqtrace_exemplars"),
    (["--telemetry=false", "--slo_p99_ms=100"], "telemetry"),
    (["--telemetry=false", "--reqtrace_ring=1024"], "telemetry"),
    (["--telemetry=false", "--reqtrace_exemplars=9"], "telemetry"),
])
def test_reqtrace_flag_validators_reject_at_parse(fresh_flags, argv,
                                                  msg):
    with pytest.raises(ValueError, match=msg):
        flags.FLAGS._parse(argv)


def test_reqtrace_flag_defaults_and_armed_pair(fresh_flags):
    flags.FLAGS._parse([])
    assert flags.FLAGS.slo_p99_ms == 0.0
    assert flags.FLAGS.reqtrace_ring == 512
    flags.FLAGS._reset()
    flags.FLAGS._parse(["--telemetry=false"])  # defaults stay legal
    flags.FLAGS._reset()
    flags.FLAGS._parse(["--slo_p99_ms=200", "--slo_target_pct=95"])
    assert flags.FLAGS.slo_target_pct == 95.0


def test_configure_from_flags_respects_telemetry(fresh_flags):
    flags.FLAGS._parse(["--slo_p99_ms=100"])
    plane = reqtrace.configure_from_flags(flags.FLAGS)
    assert plane is not None and plane.slo is not None
    assert plane.slo.p99_ms == 100.0
    flags.FLAGS._reset()
    flags.FLAGS._parse(["--telemetry=false"])
    assert reqtrace.configure_from_flags(flags.FLAGS) is None


def test_telemetry_off_leaves_ids_but_no_records(tmp_path):
    reqtrace.configure(enabled=False)
    eng, _ = _host_engine(tmp_path)
    b = _predict_batcher(eng)
    client = InProcessClient(predict_batcher=b)
    _out, meta = client.predict_ex(np.zeros(64, np.float32))
    assert meta["request_id"].startswith("req-")  # the wire contract
    assert "phases_ms" not in meta               # no plane, no record
    b.close()


# ----------------------------------------------------------- bench drill


def test_bench_reqtrace_phase_fields_non_null():
    import bench

    rec = bench.reqtrace_phase()
    assert rec.get("reqtrace_error") is None, rec
    assert rec["reqtrace_requests_total"] == bench.REQTRACE_REQUESTS
    assert rec["reqtrace_complete_pct"] == 100.0
    assert rec["reqtrace_p99_phase"] in reqtrace.PHASES
    assert rec["reqtrace_slo_compliant_pct"] is not None
    assert rec["reqtrace_overhead_pct"] is not None
    assert rec["reqtrace_overhead_pct"] < 2.0


def test_bench_degraded_record_keeps_reqtrace_fields():
    import bench

    rec = bench.degraded_record("UNAVAILABLE: forced", {},
                                cpu_smoke=False)
    for k in ("reqtrace_requests_total", "reqtrace_complete_pct",
              "reqtrace_p99_phase", "reqtrace_slo_compliant_pct",
              "reqtrace_overhead_pct"):
        assert rec[k] is not None, (k, rec.get("reqtrace_error"))
