"""dttsan — the static concurrency analyzer (tools/dttsan/).

Four layers: (1) per-pass fixture pairs — one minimal violating
snippet, one conforming — under tests/san_fixtures/; (2) the REPO-WIDE
run: zero non-baselined findings with the checked-in baseline and
registry, inside the <15s acceptance budget, with registry drift
failing BOTH directions; (3) the CLI surface (--json, exit codes,
--threads); (4) regression tests for the real concurrency bugs
dttsan's bring-up surfaced and fixed (the CheckpointWatcher stop/
restart race, unserialized engine reloads + unguarded counters, the
unbounded CompileSentry recompile ring, the Checkpointer pending-error
read outside its cv)."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.dttsan import (  # noqa: E402
    ALL_PASSES,
    run_san,
    threads_table,
)
from tools.dttsan.inventory import discover_roots  # noqa: E402

FIXTURES = os.path.join(REPO, "tests", "san_fixtures")

_EMPTY_BASELINE = os.path.join(FIXTURES, "empty_baseline.json")
_EMPTY_REGISTRY = os.path.join(FIXTURES, "empty_registry.json")


@pytest.fixture(scope="module", autouse=True)
def empty_files():
    for path in (_EMPTY_BASELINE, _EMPTY_REGISTRY):
        with open(path, "w") as f:
            json.dump({"version": 1, "entries": []}, f)
    yield
    for path in (_EMPTY_BASELINE, _EMPTY_REGISTRY):
        os.remove(path)


def _san(root, targets, registry=None):
    return run_san(
        root=os.path.join(FIXTURES, root) if root else FIXTURES,
        baseline_path=_EMPTY_BASELINE, targets=targets,
        registry_path=registry or _EMPTY_REGISTRY)


def _keys(res, rule):
    return sorted(f.key for f in res.findings if f.rule == rule)


# ---------------------------------------------------- per-pass fixtures


def test_san001_registry_drift_both_directions():
    """Orphan (discovered root missing from the registry) AND phantom
    (registry entry with no discovered root) both fail — the registry
    tracks live concurrency exactly."""
    root = os.path.join(FIXTURES, "san001_registry")
    orphan = _san("san001_registry", ("code.py",))
    assert _keys(orphan, "SAN001") == [
        "thread:code.py:Poller.__init__:self._loop"]
    assert "unregistered" in orphan.findings[0].message
    clean = _san("san001_registry", ("code.py",),
                 registry=os.path.join(root, "registry_good.json"))
    assert clean.findings == [], [f.format() for f in clean.findings]
    phantom = _san("san001_registry", ("code.py",),
                   registry=os.path.join(root, "registry_bad.json"))
    msgs = {f.key: f.message for f in phantom.findings
            if f.rule == "SAN001"}
    assert "thread:code.py:Poller.__init__:self._gone_loop" in msgs
    assert "phantom" in msgs[
        "thread:code.py:Poller.__init__:self._gone_loop"]


def test_san001_registry_entries_require_notes():
    from tools.dttsan.inventory import load_registry

    bad = os.path.join(FIXTURES, "noteless.json")
    with open(bad, "w") as f:
        json.dump({"version": 1, "entries": [{"key": "thread:x:y:z"}]},
                  f)
    try:
        with pytest.raises(ValueError, match="note"):
            load_registry(bad)
    finally:
        os.remove(bad)


def test_san002_fixture_pair():
    bad = _san("", ("san002_bad.py",))
    keys = _keys(bad, "SAN002")
    assert "san002_bad.py:Worker.naked:unguarded-write" in keys
    assert "san002_bad.py:Worker.count:mixed-locks" in keys
    assert "san002_bad.py:Worker.guarded:unguarded-read" in keys
    good = _san("", ("san002_good.py",))
    assert _keys(good, "SAN002") == []


def test_san003_fixture_pair():
    bad = _san("", ("san003_bad.py",))
    keys = _keys(bad, "SAN003")
    assert any(k.startswith("lock-cycle:") for k in keys), keys
    assert any("wait-no-while" in k and "bad_wait" in k for k in keys)
    assert any("notify-unheld" in k and "bad_notify" in k for k in keys)
    assert any("blocking-held" in k and "slow_under_lock" in k
               for k in keys)
    assert any("wait-holding" in k and "wait_holding_other" in k
               for k in keys)
    good = _san("", ("san003_good.py",))
    assert _keys(good, "SAN003") == []


def test_san004_fixture_pair():
    bad = _san("", ("san004_bad.py",))
    keys = _keys(bad, "SAN004")
    assert any("stop-reuse" in k and "Restartable.start" in k
               for k in keys), keys
    assert any("ring-unbounded" in k and "_ring" in k for k in keys)
    assert any("thread-hygiene" in k and "leak" in k for k in keys)
    good = _san("", ("san004_good.py",))
    assert _keys(good, "SAN004") == []


def test_inventory_discovers_every_root_kind():
    """The repo walk must see every kind the registry carries: threads,
    timers, handler classes, excepthook/atexit/signal hooks, and crash
    contexts — the Supervisor-parity thread plane enumerated."""
    from tools.dttlint import RepoIndex

    roots, _bad = discover_roots(RepoIndex(REPO))
    kinds = {r.kind for r in roots}
    assert {"thread", "timer", "handler", "excepthook", "atexit",
            "signal", "crash"} <= kinds
    keys = {r.key for r in roots}
    # the load-bearing roots by name (a rename must be a conscious act)
    for needle in ("DynamicBatcher.__init__:self._worker_loop",
                   "DynamicBatcher.__init__:self._expiry_loop",
                   "CheckpointWatcher.start:self._loop",
                   "Checkpointer._submit_flat:self._writer_loop",
                   "prefetch_to_device:_worker",
                   "Watchdog.arm:self._loop",
                   "Supervisor._install_signal_handlers:_handler"):
        assert any(needle in k for k in keys), needle


# ------------------------------------------------------- repo-wide run


def test_repo_is_race_free_with_checked_in_baseline():
    """THE gate: the whole walk set has zero non-baselined findings,
    zero stale suppressions, and zero registry drift, inside the <15s
    acceptance budget — every baseline entry still matches a real
    finding and carries its reason."""
    t0 = time.perf_counter()
    res = run_san()
    dt = time.perf_counter() - t0
    assert res.findings == [], \
        "new findings:\n" + "\n".join(f.format() for f in res.findings)
    assert res.stale == [], res.stale
    assert tuple(res.rules) == ALL_PASSES
    assert dt < 15.0, f"dttsan took {dt:.1f}s (>15s acceptance budget)"
    assert res.baselined, "baseline is empty — update this test if " \
                          "the tree went fully clean"
    from tools.dttsan import load_baseline

    entries = load_baseline()
    assert all(e["reason"] for e in entries)
    assert {(f.rule, f.key) for f in res.baselined} == \
        {(e["rule"], e["key"]) for e in entries}
    # the report facts bench's consan_phase emits
    assert res.report["threads_total"] > 0
    assert res.report["locks_total"] > 0
    assert res.report["shared_attrs"] > 0


def test_repo_registry_drift_fails_both_directions(tmp_path):
    """Against the REAL tree: a registry missing one live root fails
    (orphan), and one carrying an extra dead key fails (phantom)."""
    real = json.load(open(os.path.join(REPO, "tools", "dttsan",
                                       "registry.json")))
    entries = real["entries"]
    missing = tmp_path / "missing.json"
    json.dump({"version": 1, "entries": entries[1:]}, open(missing, "w"))
    res = run_san(registry_path=str(missing))
    assert any(f.rule == "SAN001" and entries[0]["key"] == f.key
               for f in res.findings)
    extra = tmp_path / "extra.json"
    json.dump({"version": 1, "entries": entries + [
        {"key": "thread:no/such/file.py:Gone.start:self._loop",
         "note": "a thread that was deleted"}]}, open(extra, "w"))
    res = run_san(registry_path=str(extra))
    assert any(f.rule == "SAN001" and "phantom" in f.message
               for f in res.findings)


def test_stale_suppression_fails_loudly(tmp_path):
    base = tmp_path / "baseline.json"
    real = json.load(open(os.path.join(REPO, "tools", "dttsan",
                                       "baseline.json")))
    base.write_text(json.dumps({"version": 1, "entries":
                                real["entries"] + [
        {"rule": "SAN002",
         "key": "no/such/file.py:Gone.attr:unguarded-write",
         "reason": "left over from deleted code"}]}))
    res = run_san(baseline_path=str(base))
    assert not res.ok
    assert res.stale == [
        "SAN002:no/such/file.py:Gone.attr:unguarded-write"]


def test_baseline_reason_is_mandatory(tmp_path):
    from tools.dttsan import load_baseline

    base = tmp_path / "noreason.json"
    base.write_text(json.dumps({"version": 1, "entries": [
        {"rule": "SAN002", "key": "x"}]}))
    with pytest.raises(ValueError, match="reason"):
        load_baseline(str(base))


def test_finding_keys_are_line_number_free():
    res = _san("", ("san002_bad.py", "san003_bad.py", "san004_bad.py"))
    import re

    for f in res.findings:
        assert not re.search(r":\d+$", f.key), \
            f"key {f.key!r} ends in what looks like a line number"


# ------------------------------------------------------------------ CLI


def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.dttsan", *args],
        capture_output=True, text=True, cwd=REPO)


def test_cli_exits_zero_and_emits_json():
    p = _cli("--json")
    assert p.returncode == 0, p.stdout + p.stderr
    out = json.loads(p.stdout)
    assert out["ok"] and out["findings"] == []
    assert list(out["rules"]) == list(ALL_PASSES)
    assert out["report"]["threads_total"] > 0


def test_cli_exits_nonzero_on_stale(tmp_path):
    base = tmp_path / "baseline.json"
    real = json.load(open(os.path.join(REPO, "tools", "dttsan",
                                       "baseline.json")))
    base.write_text(json.dumps({"version": 1, "entries":
                                real["entries"] + [
        {"rule": "SAN002", "key": "gone", "reason": "stale"}]}))
    p = _cli("--baseline", str(base))
    assert p.returncode == 1
    assert "STALE" in p.stdout


def test_cli_threads_prints_the_inventory():
    p = _cli("--threads")
    assert p.returncode == 0, p.stdout + p.stderr
    assert "self._worker_loop" in p.stdout
    assert "DynamicBatcher._cv" in p.stdout  # guarding-lock column
    rows = threads_table()
    worker = next(r for r in rows
                  if r["target"] == "self._worker_loop")
    assert "_queue" in worker["shared_attrs"]
    assert any("_cv" in lk for lk in worker["locks"])


def test_analyze_runs_all_three_with_one_exit_code():
    """The umbrella: dttlint + dttcheck + dttsan, merged exit 0 on the
    clean tree (dttcheck in its own CPU-mesh subprocess), < 30s."""
    t0 = time.perf_counter()
    p = subprocess.run(
        [sys.executable, "-m", "tools.analyze", "--json"],
        capture_output=True, text=True, cwd=REPO)
    dt = time.perf_counter() - t0
    assert p.returncode == 0, p.stdout + p.stderr
    out = json.loads(p.stdout.strip().splitlines()[-1])
    assert out["ok"]
    for name in ("dttlint", "dttcheck", "dttsan"):
        assert out[name]["ok"], out[name]
    assert dt < 30.0, f"analyze took {dt:.1f}s (>30s acceptance)"


# --------------------------------------- regressions for the r20 fixes


class _TinyModel:
    """Host-only serving model: logits = x @ w + b (the bench shape)."""

    @staticmethod
    def apply(params, x):
        return np.asarray(x) @ params["w"] + params["b"]


def _engine(tmp_path, step=10):
    from distributed_tensorflow_tpu.checkpoint.checkpoint import (
        save_checkpoint,
    )
    from distributed_tensorflow_tpu.serving.engine import InferenceEngine

    rng = np.random.default_rng(0)
    params = {"w": rng.standard_normal((8, 4)).astype(np.float32),
              "b": np.zeros(4, np.float32)}
    d = str(tmp_path / "ckpts")
    save_checkpoint(d, {"params": params}, step)
    return InferenceEngine(_TinyModel(), d, jit=False,
                           params_template=params), d, params


def test_watcher_restart_after_close_is_alive(tmp_path):
    """The stop/restart race dttsan SAN004 named: close() then start()
    used to launch a thread that observed the still-set stop event and
    exited immediately — a silently dead watcher. A restarted watcher
    must hot-swap again."""
    from distributed_tensorflow_tpu.checkpoint.checkpoint import (
        save_checkpoint,
    )
    from distributed_tensorflow_tpu.serving.engine import (
        CheckpointWatcher,
    )

    eng, d, params = _engine(tmp_path)
    w = CheckpointWatcher(eng, interval_s=0.05).start()
    first = w._thread
    assert first is not None and first.is_alive()
    w.close()
    assert w._thread is None
    w.start()
    second = w._thread
    assert second is not None and second.is_alive()
    assert second is not first
    # and it still does its job: a newer checkpoint gets swapped in
    save_checkpoint(d, {"params": params}, 20)
    deadline = time.monotonic() + 5.0
    while eng.step < 20 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert eng.step == 20
    w.close()


def test_watcher_restart_uses_a_fresh_stop_event(tmp_path):
    eng, _d, _p = _engine(tmp_path)
    from distributed_tensorflow_tpu.serving.engine import (
        CheckpointWatcher,
    )

    w = CheckpointWatcher(eng, interval_s=30.0).start()
    ev1 = w._stop
    w.close()
    assert ev1.is_set()
    w.start()
    assert w._stop is not ev1 and not w._stop.is_set()
    w.close()


def test_concurrent_reloads_serialize_and_step_never_regresses(
        tmp_path):
    """The watcher tick racing check_now(): both used to restore
    concurrently, and the slower (older) restore could swap AFTER a
    newer one — a served-version regression. Reloads are serialized
    now; under a hammering mix of writers and reloaders the served
    step must be non-decreasing and land at the newest."""
    from distributed_tensorflow_tpu.checkpoint.checkpoint import (
        save_checkpoint,
    )

    eng, d, params = _engine(tmp_path)
    observed: list[int] = []
    stop = threading.Event()
    regressions: list[tuple] = []

    def reloader():
        last = -1
        while not stop.is_set():
            eng.reload_if_newer()
            s = eng.step
            if s < last:
                regressions.append((last, s))
            last = s
            observed.append(s)

    threads = [threading.Thread(target=reloader, daemon=True)
               for _ in range(3)]
    for t in threads:
        t.start()
    for step in range(11, 31):
        save_checkpoint(d, {"params": params}, step)
    deadline = time.monotonic() + 10.0
    while eng.step < 30 and time.monotonic() < deadline:
        time.sleep(0.01)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    assert regressions == []
    assert eng.step == 30
    snap = eng.counters_snapshot()
    assert snap["reloads"] >= 1
    assert eng.stats()["step"] == 30


def test_compile_sentry_ring_is_bounded():
    """The recompile ring dttsan SAN004 named: deque() without maxlen
    relied on pruning logic for its bound. Bounded by construction now
    — and the storm report still trips (maxlen is budget+1, exactly
    enough for len > budget)."""
    from distributed_tensorflow_tpu.utils.resources import CompileSentry

    snt = CompileSentry(budget=3, window_s=3600.0)
    assert snt._recent.maxlen == 4
    for i in range(8):
        snt.observe("site", (i,))
    assert snt.storms >= 1
    assert len(snt._recent) <= snt._recent.maxlen
    unbudgeted = CompileSentry(budget=0)
    assert unbudgeted._recent.maxlen is not None


def test_tracer_flush_rebinds_handle_after_sink_race(tmp_path):
    """A configure_sink racing between flush()'s path snapshot and its
    file write could leave the handle bound to the OLD path forever —
    every later flush misdirecting spans into the previous run's file.
    flush() now re-checks the handle's path against its snapshot."""
    from distributed_tensorflow_tpu.utils.telemetry import Tracer

    old = str(tmp_path / "run1" / "spans.jsonl")
    new = str(tmp_path / "run2" / "spans.jsonl")
    tr = Tracer()
    tr.configure_sink(old)
    with tr.span("warm"):
        pass
    tr.flush()  # binds the handle to run1
    # the race's post state: _path moved on, handle still bound to old
    tr.configure_sink(new)
    os.makedirs(os.path.dirname(old), exist_ok=True)
    tr._file = open(old, "a")
    tr._file_path = old
    with tr.span("after"):
        pass
    tr.flush()
    assert "after" in open(new).read()
    assert "after" not in open(old).read()
    tr.configure_sink(None)


def test_checkpointer_pending_error_read_under_cv(tmp_path):
    """The lock-free test-then-clear of _error could drop a writer
    error landing between the two; the read-and-clear now happens
    under the cv and still surfaces exactly once."""
    from distributed_tensorflow_tpu.checkpoint.checkpoint import (
        Checkpointer,
    )

    ck = Checkpointer(str(tmp_path / "ck"), background=True,
                      save_model_secs=1)
    err = RuntimeError("disk gone")
    with ck._cv:
        ck._error = err
    with pytest.raises(RuntimeError, match="disk gone"):
        ck._raise_pending_error()
    ck._raise_pending_error()  # cleared: second call is quiet
