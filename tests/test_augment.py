"""On-device augmentation (ops/augment.py): geometry, dtype and
determinism of the crop+flip transform, and its wiring into the compiled
steps (host-fed and device-resident)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.ops.augment import make_augment, random_crop_flip


def _imgs(b=8, h=8, w=8, c=3, dtype=np.float32, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.uniform(0, 1, (b, h, w, c)).astype(dtype))


def test_shape_and_dtype_preserved():
    for dtype in (np.float32, np.uint8):
        x = (_imgs(dtype=np.float32) * 255).astype(dtype) if dtype == np.uint8 \
            else _imgs()
        y = random_crop_flip(x, jax.random.PRNGKey(0), pad=2)
        assert y.shape == x.shape and y.dtype == x.dtype


def test_pad0_noflip_is_identity():
    x = _imgs()
    y = random_crop_flip(x, jax.random.PRNGKey(0), pad=0, flip=False)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))


def test_deterministic_per_key():
    x = _imgs()
    a = random_crop_flip(x, jax.random.PRNGKey(7), pad=3)
    b = random_crop_flip(x, jax.random.PRNGKey(7), pad=3)
    c = random_crop_flip(x, jax.random.PRNGKey(8), pad=3)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_crops_are_translations():
    """With flip off, each output row range must be a contiguous window of
    the zero-padded input — check by matching every example against all
    possible offsets."""
    x = _imgs(b=4, h=6, w=6, c=1)
    pad = 2
    y = np.asarray(random_crop_flip(x, jax.random.PRNGKey(3), pad=pad,
                                    flip=False))
    padded = np.pad(np.asarray(x), ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    for i in range(x.shape[0]):
        found = any(
            np.array_equal(y[i], padded[i, r:r + 6, s:s + 6])
            for r in range(2 * pad + 1) for s in range(2 * pad + 1)
        )
        assert found, f"example {i} is not a crop of its padded input"


def test_flip_flips_some():
    x = _imgs(b=64)
    y = np.asarray(random_crop_flip(x, jax.random.PRNGKey(1), pad=0,
                                    flip=True))
    xf = np.asarray(x)[:, :, ::-1, :]
    flipped = sum(np.array_equal(y[i], xf[i]) for i in range(64))
    kept = sum(np.array_equal(y[i], np.asarray(x)[i]) for i in range(64))
    assert flipped + kept == 64
    assert 10 < flipped < 54  # ~Binomial(64, 0.5)


def test_make_augment_flat_roundtrip():
    meta = {"image_size": 8, "channels": 3}
    aug = make_augment(meta, pad=0, flip=False)
    x = _imgs().reshape(8, -1)
    y = aug(x, jax.random.PRNGKey(0))
    assert y.shape == x.shape
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))


def test_augmented_train_step_runs():
    from distributed_tensorflow_tpu.models import get_model
    from distributed_tensorflow_tpu.training import (
        create_train_state,
        make_train_step,
        sgd,
    )

    meta = {"image_size": 8, "channels": 3}
    model = get_model("resnet20", image_size=8, channels=3, num_classes=10)
    opt = sgd(0.05)
    state = create_train_state(model, opt, seed=0)
    step = make_train_step(model, opt, keep_prob=1.0, donate=False,
                           augment_fn=make_augment(meta))
    x = jax.random.normal(jax.random.key(0), (8, 8 * 8 * 3))
    y = jax.nn.one_hot(jnp.arange(8) % 10, 10)
    state, m = step(state, (x, y))
    assert int(state.step) == 1 and np.isfinite(float(m["loss"]))


def test_augmented_device_step_runs():
    from distributed_tensorflow_tpu.data.device_data import DeviceData
    from distributed_tensorflow_tpu.models import DeepCNN
    from distributed_tensorflow_tpu.training import create_train_state, sgd
    from distributed_tensorflow_tpu.training.device_step import (
        make_device_train_step,
    )

    n = 64
    data = DeviceData(
        jnp.asarray((np.arange(n * 784) % 255).astype(np.uint8).reshape(n, 784)),
        jnp.asarray((np.arange(n) % 10).astype(np.int32)),
    )
    model = DeepCNN()
    opt = sgd(0.1)
    state = create_train_state(model, opt, seed=0)
    aug = make_augment({"image_size": 28, "channels": 1}, pad=2, flip=False)
    fn = make_device_train_step(model, opt, 16, keep_prob=0.75, chunk=2,
                                donate=False, augment_fn=aug)
    state, m = fn(state, data)
    assert int(state.step) == 2 and np.isfinite(float(m["loss"]))


def test_augment_does_not_perturb_other_streams():
    """Enabling augmentation must not change the dropout/sampling key
    evolution: the post-step state.rng is identical with and without."""
    from distributed_tensorflow_tpu.models import DeepCNN
    from distributed_tensorflow_tpu.training import (
        create_train_state,
        make_train_step,
        sgd,
    )

    model = DeepCNN()
    opt = sgd(0.05)
    aug = make_augment({"image_size": 28, "channels": 1}, pad=2)
    x = jnp.ones((4, 784), jnp.float32)
    y = jax.nn.one_hot(jnp.arange(4) % 10, 10)
    s_plain = create_train_state(model, opt, seed=0)
    s_aug = create_train_state(model, opt, seed=0)
    plain = make_train_step(model, opt, keep_prob=0.75, donate=False)
    auged = make_train_step(model, opt, keep_prob=0.75, donate=False,
                            augment_fn=aug)
    s_plain, _ = plain(s_plain, (x, y))
    s_aug, _ = auged(s_aug, (x, y))
    np.testing.assert_array_equal(np.asarray(s_plain.rng),
                                  np.asarray(s_aug.rng))
