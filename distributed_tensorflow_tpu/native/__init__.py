"""ctypes bindings for the native host data plane (fastdata.cpp).

Builds the shared library on first import with g++ (cached next to the
source); every entry point has a NumPy fallback in data/datasets.py, so a
missing toolchain degrades gracefully — ``available()`` reports which path
is active.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "fastdata.cpp")
_SO = os.path.join(_DIR, "libfastdata.so")

_lib = None
_lock = threading.Lock()
_build_error: str | None = None


def _build() -> str | None:
    """Compile fastdata.cpp -> libfastdata.so. Returns error string or None."""
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
        _SRC, "-o", _SO,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        return f"g++ unavailable: {e}"
    if proc.returncode != 0:
        return f"g++ failed: {proc.stderr[-500:]}"
    return None


def _load():
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            _build_error = _build()
            if _build_error is not None:
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            _build_error = f"dlopen failed: {e}"
            return None
        lib.idx_header.restype = ctypes.c_int
        lib.idx_header.argtypes = [ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
                                   ctypes.POINTER(ctypes.c_int64),
                                   ctypes.POINTER(ctypes.c_int64)]
        lib.idx_read_u8.restype = ctypes.c_int64
        lib.idx_read_u8.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                    ctypes.c_void_p, ctypes.c_int64]
        lib.gather_normalize.restype = None
        lib.gather_normalize.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                         ctypes.c_void_p, ctypes.c_int64,
                                         ctypes.c_void_p, ctypes.c_int]
        lib.onehot_gather.restype = None
        lib.onehot_gather.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                      ctypes.c_int64, ctypes.c_int64,
                                      ctypes.c_void_p]
        lib.permutation.restype = None
        lib.permutation.argtypes = [ctypes.c_int64, ctypes.c_uint64,
                                    ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def build_error() -> str | None:
    _load()
    return _build_error


def read_idx_u8(path: str) -> np.ndarray | None:
    """Native IDX reader for uncompressed u8 files; None if inapplicable."""
    lib = _load()
    if lib is None or path.endswith(".gz"):
        return None
    ndim = ctypes.c_int()
    dims = (ctypes.c_int64 * 8)()
    off = ctypes.c_int64()
    dtype = lib.idx_header(path.encode(), ctypes.byref(ndim), dims,
                           ctypes.byref(off))
    if dtype != 0x08:
        return None
    shape = tuple(dims[i] for i in range(ndim.value))
    n = int(np.prod(shape)) if shape else 0
    out = np.empty(n, np.uint8)
    got = lib.idx_read_u8(path.encode(), off.value,
                          out.ctypes.data_as(ctypes.c_void_p), n)
    if got != n:
        return None
    return out.reshape(shape)


def gather_normalize(images_u8: np.ndarray, idx: np.ndarray,
                     threads: int = 4) -> np.ndarray | None:
    """out[i] = images_u8[idx[i]] / 255 as float32; None if lib missing."""
    lib = _load()
    if lib is None:
        return None
    images_u8 = np.ascontiguousarray(images_u8)
    idx = np.ascontiguousarray(idx, np.int64)
    pixels = images_u8.shape[1]
    out = np.empty((len(idx), pixels), np.float32)
    lib.gather_normalize(images_u8.ctypes.data_as(ctypes.c_void_p),
                         pixels, idx.ctypes.data_as(ctypes.c_void_p),
                         len(idx), out.ctypes.data_as(ctypes.c_void_p),
                         threads)
    return out


def onehot_gather(labels: np.ndarray, idx: np.ndarray, classes: int) -> np.ndarray | None:
    lib = _load()
    if lib is None:
        return None
    labels = np.ascontiguousarray(labels, np.int64)
    idx = np.ascontiguousarray(idx, np.int64)
    out = np.zeros((len(idx), classes), np.float32)
    lib.onehot_gather(labels.ctypes.data_as(ctypes.c_void_p),
                      idx.ctypes.data_as(ctypes.c_void_p), len(idx), classes,
                      out.ctypes.data_as(ctypes.c_void_p))
    return out


def permutation(n: int, seed: int) -> np.ndarray | None:
    lib = _load()
    if lib is None:
        return None
    out = np.empty(n, np.int64)
    lib.permutation(n, seed & 0xFFFFFFFFFFFFFFFF,
                    out.ctypes.data_as(ctypes.c_void_p))
    return out
