"""bench.py phases exercised on the 8-device virtual mesh (weak spot from
round 1: the multi-chip branch only ran when real hardware had >1 chip).
Constants are shrunk via monkeypatch; the point is that every branch —
mesh build, sharded prefetch staging, dp eval on the device-resident test
set, the feed-dict baseline — compiles and executes, not the numbers."""

import time

import jax
import numpy as np
import pytest

_PRNG_BEFORE_BENCH_IMPORT = jax.config.jax_default_prng_impl

import bench  # noqa: E402 — the capture above must precede this import
from distributed_tensorflow_tpu.data import read_data_sets


@pytest.fixture(scope="module")
def ds(tmp_path_factory):
    # synthetic (no IDX files in the tmp dir); 2000-example test split is
    # divisible by 8 so convergence_phase takes the dp-eval branch
    return read_data_sets(str(tmp_path_factory.mktemp("no-data")), one_hot=True)


# the 8-mesh arm compiles the full host-fed wire path over the virtual
# mesh — 341s on the r23 tier-1 audit, the single largest line in the
# kill window, for a link-bound rate DTP001 exempts from banding; the
# 1-chip arm keeps the phase's tier-1 coverage
@pytest.mark.parametrize(
    "n_chips", [1, pytest.param(8, marks=pytest.mark.slow)])
def test_throughput_phase_runs(monkeypatch, ds, n_chips):
    monkeypatch.setattr(bench, "PER_CHIP_BATCH", 16)
    monkeypatch.setattr(bench, "WIRE_TIMED_STEPS", 4)
    rate = bench.throughput_phase(ds, n_chips)
    assert rate > 0 and np.isfinite(rate)


@pytest.mark.parametrize("n_chips", [1, 8])
def test_device_resident_phase_runs(monkeypatch, ds, n_chips):
    monkeypatch.setattr(bench, "PER_CHIP_BATCH", 16)
    monkeypatch.setattr(bench, "CHUNK", 3)
    monkeypatch.setattr(bench, "TIMED_CHUNKS", 2)
    rate = bench.device_resident_phase(ds, n_chips)
    assert rate > 0 and np.isfinite(rate)


@pytest.mark.parametrize("n_chips", [1, 8])
def test_convergence_phase_runs(monkeypatch, ds, n_chips):
    monkeypatch.setattr(bench, "CONVERGE_BATCH", 16)
    monkeypatch.setattr(bench, "CONVERGE_MAX_STEPS", 12)
    monkeypatch.setattr(bench, "CONVERGE_EVAL_EVERY", 6)
    out = bench.convergence_phase(ds, n_chips)
    assert 0.0 <= out["test_accuracy"] <= 1.0
    assert out["target_accuracy"] == bench.TARGET_ACC
    # 12 tiny steps will not reach 99%; the fields must say so honestly
    if out["seconds_to_target"] is None:
        assert out["steps_to_target"] is None


def test_resnet_phase_runs(monkeypatch, tmp_path):
    monkeypatch.setattr(bench, "RESNET_PER_CHIP_BATCH", 4)
    monkeypatch.setattr(bench, "RESNET_TIMED_CHUNKS", 1)
    monkeypatch.setattr(bench, "RESNET_CHUNK", 2)
    # hermetic: an empty data_dir pins the synthetic CIFAR fallback
    rate, source = bench.resnet_phase(8, data_dir=str(tmp_path / "no-cifar"))
    assert rate > 0 and np.isfinite(rate)
    assert source == "synthetic"


def test_ps_emulation_phase_runs(monkeypatch, ds):
    monkeypatch.setattr(bench, "PS_BATCH", 16)
    monkeypatch.setattr(bench, "PS_STEPS", 3)
    rate = bench.ps_emulation_phase(ds)
    assert rate > 0 and np.isfinite(rate)


def test_feeddict_baseline_runs(monkeypatch, ds):
    monkeypatch.setattr(bench, "FEEDDICT_BATCH", 16)
    monkeypatch.setattr(bench, "FEEDDICT_STEPS", 3)
    rate = bench.feeddict_baseline_phase(ds, 8)
    assert rate > 0 and np.isfinite(rate)


def test_sync_every_matches_backend():
    assert bench._sync_every(1) == 0
    expected = 16 if jax.default_backend() == "cpu" else 0
    assert bench._sync_every(8) == expected


def test_bench_import_does_not_flip_global_prng():
    """Regression: bench.py selects the rbg PRNG inside main() (scoped),
    not at import time — this module imports bench, and a module-level
    config flip leaked rbg into every test module collected afterwards
    (changing init distributions under other tests' seeds). Assert the
    import left the impl exactly as it found it."""
    assert jax.config.jax_default_prng_impl == _PRNG_BEFORE_BENCH_IMPORT


def test_convergence_phase_fashion_target(monkeypatch, ds):
    """The fashion phase reuses convergence_phase with its own target and
    budget; the reported target_accuracy must follow the parameter.
    CONVERGE_BATCH shrinks like its siblings above — at the default 128
    this single test paid minutes of bf16-emulated CPU chunks (the r23
    tier-1 audit's worst offender) for an assertion about parameter
    plumbing."""
    monkeypatch.setattr(bench, "CONVERGE_BATCH", 16)
    monkeypatch.setattr(bench, "CONVERGE_EVAL_EVERY", 5)
    out = bench.convergence_phase(ds, 1, target_acc=0.5, max_steps=20)
    assert out["target_accuracy"] == 0.5
    assert out["steps_to_target"] is None or out["steps_to_target"] <= 20


def test_lm_longctx_phase_runs(monkeypatch):
    monkeypatch.setattr(bench, "LM_SEQ_LEN", 64)
    monkeypatch.setattr(bench, "LM_BATCH", 4)
    monkeypatch.setattr(bench, "LM_D_MODEL", 32)
    monkeypatch.setattr(bench, "LM_ATTN_BLOCK", 16)
    monkeypatch.setattr(bench, "LM_TIMED_STEPS", 2)
    out = bench.lm_longctx_phase()
    assert out["lm_4k_tokens_per_sec_per_chip"] > 0
    assert out["lm_seq_len"] == 64


# ---- forced-outage resilience (VERDICT r4 #1: BENCH_r04.json was rc=1
# with a bare stack trace when the tunnel was down at capture time; the
# artifact must instead be one parsable degraded JSON line) ----

def _failing_probe():
    return False, "backend init hung > 120s (tunnel outage signature)"


def test_init_retry_bounded_and_backed_off():
    sleeps = []
    info = bench._init_backend_with_retry(
        attempts=4, backoffs=(30.0, 60.0, 120.0),
        probe=_failing_probe, sleep=sleeps.append)
    assert info["ok"] is False
    assert info["attempts"] == 4
    # backoff between attempts only (not after the last), clamped to the
    # final backoff value; total wait is bounded and reported
    assert sleeps == [30.0, 60.0, 120.0]
    assert info["waited_s"] == 210.0
    assert "outage" in info["error"]


def test_init_retry_recovers_mid_sequence():
    calls = {"n": 0}

    def flaky_probe():
        calls["n"] += 1
        return (calls["n"] >= 3), "UNAVAILABLE"

    sleeps = []
    info = bench._init_backend_with_retry(
        attempts=4, backoffs=(1.0, 2.0, 4.0),
        probe=flaky_probe, sleep=sleeps.append)
    assert info["ok"] is True and info["attempts"] == 3
    assert sleeps == [1.0, 2.0]


def test_degraded_record_shape():
    """Pin the outage artifact's shape: headline keys present (null), the
    tpu_unavailable flag, the error, and init accounting — and the whole
    thing must survive a json round-trip as one line."""
    import json

    rec = bench.degraded_record(
        "jax.errors.JaxRuntimeError: UNAVAILABLE: tunnel down",
        {"ok": False, "attempts": 4, "waited_s": 210.0},
        cpu_smoke=False)
    line = json.dumps(rec)
    assert "\n" not in line
    back = json.loads(line)
    assert back["tpu_unavailable"] is True
    assert back["metric"] == "mnist_images_per_sec_per_chip"
    assert back["value"] is None and back["vs_baseline"] is None
    assert back["unit"] == "images/sec/chip"
    assert "UNAVAILABLE" in back["error"]
    assert back["init_attempts"] == 4 and back["init_waited_s"] == 210.0


def test_degraded_record_keeps_partial_results():
    """A mid-run flap must not discard phases that already completed:
    partial fields override the nulls."""
    rec = bench.degraded_record(
        "RuntimeError: remote_compile: read body: response body closed",
        {"attempts": 1, "waited_s": 0.0},
        partial={"value": 747600.0, "n_chips": 1, "data_source": "synthetic"},
        cpu_smoke=False)
    assert rec["tpu_unavailable"] is True
    assert rec["value"] == 747600.0
    assert rec["n_chips"] == 1


def test_main_emits_degraded_json_on_init_failure(monkeypatch, capsys):
    """End-to-end forced outage: main() with a dead backend prints exactly
    one parsable JSON line on stdout and returns (no exception, no trace)."""
    import json

    monkeypatch.setattr(bench, "_probe_backend", _failing_probe)
    monkeypatch.setattr(
        bench, "BACKEND_PROBE_BACKOFF_S", (0.0, 0.0, 0.0))
    monkeypatch.setattr(
        bench, "_cpu_smoke", lambda: {"ok": True, "platform": "cpu"})
    bench.main()
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["tpu_unavailable"] is True and rec["value"] is None
    assert rec["cpu_smoke"]["ok"] is True


def test_main_emits_degraded_json_on_midrun_failure(monkeypatch, capsys):
    """A phase exception after init mid-run yields the degraded line with
    the completed fields attached, not a stack-trace-only rc=1."""
    import json

    monkeypatch.setattr(bench, "_probe_backend", lambda: (True, ""))

    def exploding_phases(out):
        out["n_chips"] = 1
        out["value"] = 123.4
        raise RuntimeError("UNAVAILABLE: socket closed")

    monkeypatch.setattr(bench, "_run_phases", exploding_phases)
    bench.main()
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    rec = json.loads(lines[-1])
    assert rec["tpu_unavailable"] is True
    assert rec["value"] == 123.4 and rec["n_chips"] == 1
    assert "UNAVAILABLE" in rec["error"]


def test_main_phase_software_error_exits_nonzero(monkeypatch, capsys):
    """A mid-run exception WITHOUT an outage signature is a software
    regression: the artifact line must say phase_error (not
    tpu_unavailable) and the process must exit nonzero — the driver's
    outage handling must never swallow a real regression."""
    import json

    monkeypatch.setattr(bench, "_probe_backend", lambda: (True, ""))

    def buggy_phases(out):
        out["n_chips"] = 1
        raise KeyError("test_accuracy")  # a code bug, not the tunnel

    monkeypatch.setattr(bench, "_run_phases", buggy_phases)
    with pytest.raises(SystemExit) as exc:
        bench.main()
    assert exc.value.code == 1
    rec = json.loads(
        [l for l in capsys.readouterr().out.splitlines() if l.strip()][-1])
    assert rec["phase_error"] is True
    assert rec["tpu_unavailable"] is False
    assert rec["n_chips"] == 1


def _shrink_ppep(monkeypatch):
    monkeypatch.setattr(bench, "PP_EP_SEQ_LEN", 32)
    monkeypatch.setattr(bench, "PP_EP_VOCAB", 16)
    monkeypatch.setattr(bench, "PP_EP_D_MODEL", 32)
    monkeypatch.setattr(bench, "PP_EP_SPLIT", 64)
    monkeypatch.setattr(bench, "PP_EP_BATCH_PER_DATA_WAY", 4)
    monkeypatch.setattr(bench, "PP_EP_CHUNK", 2)
    monkeypatch.setattr(bench, "PP_EP_TIMED_CHUNKS", 1)


@pytest.mark.slow  # the compile-heavy phase bodies; the mesh paths they
                   # drive are tier-1-covered by tests/test_device_pp_ep.py
def test_pp_device_phase_runs(monkeypatch):
    _shrink_ppep(monkeypatch)
    out = bench.pp_device_phase(8)
    assert out["pp_images_per_sec_per_chip"] > 0
    assert out["pp_device_stages"] == 4
    # r7: same-session schedule A/B + analytic facts ride along
    assert out["pp_gpipe_images_per_sec_per_chip"] > 0
    assert out["pp_schedule"] == "interleaved"
    assert out["pp_virtual_stages"] == 2
    assert out["pp_interleave_speedup"] is not None


@pytest.mark.slow
def test_ep_device_phase_runs(monkeypatch):
    _shrink_ppep(monkeypatch)
    out = bench.ep_device_phase(8)
    assert out["ep_tokens_per_sec_per_chip"] > 0
    assert out["ep_device_experts"] == bench.PP_EP_EXPERTS


def test_ppep_phases_skip_on_one_chip():
    """1 chip has no model axis: the phases must report null metrics
    with a reason, not crash (the r5 hardened-artifact pattern)."""
    pp, ep = bench.pp_device_phase(1), bench.ep_device_phase(1)
    assert pp["pp_images_per_sec_per_chip"] is None
    assert ep["ep_tokens_per_sec_per_chip"] is None
    assert "pp_device_skipped" in pp and "ep_device_skipped" in ep


def test_degraded_record_nulls_ppep_keys():
    """Outage artifacts carry the PP/EP headline keys as nulls so the
    driver's schema stays stable across outages."""
    rec = bench.degraded_record("UNAVAILABLE", {}, cpu_smoke=False)
    assert rec["pp_images_per_sec_per_chip"] is None
    assert rec["ep_tokens_per_sec_per_chip"] is None


def test_pp_schedule_facts_match_analytic_formula():
    """The BENCH schedule facts must equal the analytic bubble formula
    M*V/(M*V + K - 1) for the phase's (K, M=K, V) config — the
    acceptance pin that the recorded fraction is the real cost model,
    not a hand-typed constant."""
    for ways in (2, 4):
        facts = bench._pp_schedule_facts(ways)
        v = facts["pp_virtual_stages"]
        m = ways  # the phase runs microbatches = stage count
        assert facts["pp_useful_tick_fraction"] == round(
            m * v / (m * v + ways - 1), 4)
        assert facts["pp_schedule"] == ("interleaved" if v > 1
                                        else "gpipe")
        # PP_NUM_BLOCKS=8 gives both the 2- and 4-way axes a V=2 run
        assert v == 2


def test_degraded_record_keeps_schedule_facts_non_null():
    """The r4-r5 TPU-number hole (VERDICT.md): tunnel outages null the
    rates, but the ANALYTIC schedule facts must survive so the perf
    trajectory keeps schedule-level evidence."""
    rec = bench.degraded_record("UNAVAILABLE: tunnel down", {},
                                cpu_smoke=False)
    assert rec["pp_images_per_sec_per_chip"] is None
    assert rec["pp_schedule"] == "interleaved"
    assert rec["pp_virtual_stages"] == 2
    # 2-way fallback config: K=2, M=2, V=2 -> 4/5
    assert rec["pp_useful_tick_fraction"] == 0.8
    # r16: the static-analysis facts ride the degraded record too
    # (dttlint is pure ast, no backend at all) — asserted here instead
    # of paying a second full degraded_record build
    assert rec["lint_findings_total"] == 0
    assert rec["lint_rules"] == 11
    assert rec["lint_baselined_total"] is not None
    assert rec["lint_time_s"] is not None
    # r20: the concurrency-proof facts ride the degraded record too
    # (dttsan is pure ast like dttlint — no backend at all)
    assert rec["consan_findings_total"] == 0
    assert rec["consan_threads_total"] > 0
    assert rec["consan_locks_total"] > 0
    assert rec["consan_time_s"] is not None
    # r18: the jaxpr-proof facts ride the degraded record too (the
    # dttcheck drill runs in its own CPU-mesh subprocess, no backend
    # dependence; per-process cache makes this ride-along free here)
    assert rec["jaxprcheck_findings_total"] == 0
    assert rec["jaxprcheck_modes_proven"] == 8
    assert rec["jaxprcheck_collectives_total"] > 0
    assert rec["jaxprcheck_time_s"] is not None
    # r23: the performance-contract facts ride the degraded record too
    # (dttperf is pure Python + eval_shape; per-process cache makes
    # this ride-along free here — DTP002 enforces the wiring statically)
    assert rec["perfcheck_findings_total"] == 0
    assert rec["perfcheck_scenarios_proven"] >= 13
    assert rec["perfcheck_band_pct"] is not None
    assert rec["perfcheck_time_s"] is not None


def test_degraded_record_keeps_router_facts_non_null():
    """r22: the fleet-router drill is host-only (LocalTransport, no
    chip), so its facts must survive outages — non-null in EVERY
    record, degraded included."""
    rec = bench.degraded_record("UNAVAILABLE: tunnel down", {},
                                cpu_smoke=False)
    assert rec["router_replicas"] == 2
    assert rec["router_healthy"] is not None
    assert rec["router_ejections"] >= 1  # the breaker drill tripped
    assert rec["router_retries"] is not None
    assert rec["router_hedges"] >= 1  # the hedge drill fired
    assert rec["router_overhead_ms"] is not None
    assert "router_error" not in rec


def test_pp_skip_record_carries_schedule_facts():
    """Even the 1-chip skip record reports the (analytic) schedule
    facts alongside its null rates."""
    pp = bench.pp_device_phase(1)
    assert pp["pp_images_per_sec_per_chip"] is None
    assert pp["pp_gpipe_images_per_sec_per_chip"] is None
    assert pp["pp_schedule"] == "interleaved"
    assert pp["pp_useful_tick_fraction"] == 0.8


def test_lm_largevocab_phase_runs(monkeypatch):
    monkeypatch.setattr(bench, "LM_BIGV_VOCAB", 512)
    monkeypatch.setattr(bench, "LM_BIGV_SEQ_LEN", 64)
    monkeypatch.setattr(bench, "LM_BIGV_BATCH", 2)
    monkeypatch.setattr(bench, "LM_BIGV_CE_BLOCK", 16)
    monkeypatch.setattr(bench, "LM_BIGV_TIMED_STEPS", 2)
    monkeypatch.setattr(bench, "LM_D_MODEL", 32)
    monkeypatch.setattr(bench, "LM_ATTN_BLOCK", 16)
    out = bench.lm_largevocab_phase()
    assert out["lm_bigvocab_tokens_per_sec_per_chip"] > 0
    assert out["lm_bigvocab_vocab"] == 512
    assert out["lm_bigvocab_seq_len"] == 64


# ---- r10: the dp_zero phase (replicated vs --zero 1 A/B + analytic
# memory facts; the facts must survive outages and 1-chip skips) ----


_ZERO_ANALYTIC_KEYS = (
    "zero_data_ways", "zero_opt_bytes_per_chip",
    "zero_opt_bytes_per_chip_replicated", "zero_opt_reduction",
    "zero3_param_bytes_per_chip", "zero_param_reduction",
    "zero_comm_bytes_allreduce", "zero_comm_bytes_reduce_scatter_gather",
    "zero_live_bytes_per_chip", "dp_live_bytes_per_chip",
    "zero_live_bytes_source",
)


@pytest.mark.slow
def test_dp_zero_phase_runs(monkeypatch, ds):
    monkeypatch.setattr(bench, "PER_CHIP_BATCH", 8)
    monkeypatch.setattr(bench, "CHUNK", 2)
    monkeypatch.setattr(bench, "ZERO_TIMED_CHUNKS", 2)
    out = bench.dp_zero_phase(ds, 8)
    assert out["zero_images_per_sec_per_chip"] > 0
    assert out["dp_ab_images_per_sec_per_chip"] > 0
    assert out["zero_data_ways"] == 8
    assert out["zero_opt_reduction"] >= 7.9
    for k in _ZERO_ANALYTIC_KEYS:
        assert out[k] is not None, k
    # CPU backend has no memory_stats -> the analytic totals stand in
    assert out["zero_live_bytes_source"] in ("analytic", "memory_stats")


def test_dp_zero_phase_skips_on_one_chip(ds):
    """1 chip = nothing to shard over: null rates with a reason, the
    analytic facts (2-way fallback config) still present."""
    out = bench.dp_zero_phase(ds, 1)
    assert out["zero_images_per_sec_per_chip"] is None
    assert out["dp_ab_images_per_sec_per_chip"] is None
    assert "zero_skipped" in out
    assert out["zero_data_ways"] == 2
    assert out["zero_opt_reduction"] >= 1.9


def test_degraded_record_keeps_zero_facts_non_null():
    """Outage artifacts null the measured A/B rates but carry every
    analytic ZeRO memory/comm fact (the r8-r9 hardened-artifact
    convention)."""
    rec = bench.degraded_record("UNAVAILABLE: tunnel down", {},
                                cpu_smoke=False)
    assert rec["zero_images_per_sec_per_chip"] is None
    assert rec["dp_ab_images_per_sec_per_chip"] is None
    for k in _ZERO_ANALYTIC_KEYS:
        assert rec[k] is not None, k
    # r14: the overlap phase's analytic facts ride the same record —
    # measured A/B rates null, schedule fractions/exposure non-null
    for key in bench._OVERLAP_RATE_KEYS:
        assert rec[key] is None, key
    for k in _OVERLAP_ANALYTIC_KEYS:
        assert rec[k] is not None, k
    assert rec["pp_zb_useful_tick_fraction"] > \
        rec["pp_interleaved_useful_tick_fraction"]
    assert rec["zero_live_bytes_source"] == "analytic"
    assert rec["zero_data_ways"] == 2


# ---- r14: the overlap phase (pipeline-schedule A/B + ZeRO comm
# overlap; the analytic fractions/exposure must survive outages) ----


_OVERLAP_ANALYTIC_KEYS = (
    "pp_gpipe_useful_tick_fraction",
    "pp_interleaved_useful_tick_fraction",
    "pp_zb_useful_tick_fraction", "pp_zb_ticks",
    "zero_overlap_bucket_mb", "zero_overlap_buckets",
    "zero1_exposed_comm_bytes_serial", "zero1_exposed_comm_bytes_overlap",
    "zero3_exposed_comm_bytes_serial", "zero3_exposed_comm_bytes_overlap",
)


def test_overlap_analytic_facts_pin_the_acceptance():
    """The chip-free half of the r14 acceptance: zb's useful-tick
    fraction strictly exceeds interleaved at the SAME (K, M, V), and
    the overlapped exposure is strictly below the serial exposure at
    both ZeRO levels."""
    out = bench._overlap_analytic_facts(2, 8)
    for k in _OVERLAP_ANALYTIC_KEYS:
        assert out[k] is not None, k
    assert out["pp_zb_useful_tick_fraction"] > \
        out["pp_interleaved_useful_tick_fraction"] > \
        out["pp_gpipe_useful_tick_fraction"]
    for lv in (1, 3):
        assert out[f"zero{lv}_exposed_comm_bytes_overlap"] < \
            out[f"zero{lv}_exposed_comm_bytes_serial"]


@pytest.mark.slow
def test_overlap_phase_runs(monkeypatch, ds):
    monkeypatch.setattr(bench, "PER_CHIP_BATCH", 8)
    monkeypatch.setattr(bench, "CHUNK", 2)
    monkeypatch.setattr(bench, "OVERLAP_TIMED_CHUNKS", 1)
    _shrink_ppep(monkeypatch)
    monkeypatch.setattr(bench, "PP_NUM_BLOCKS", 8)
    out = bench.overlap_phase(ds, 8)
    for key in bench._OVERLAP_RATE_KEYS:
        assert out[key] is not None and out[key] > 0, key
    for k in _OVERLAP_ANALYTIC_KEYS:
        assert out[k] is not None, k


def test_overlap_phase_skips_on_one_chip(ds):
    out = bench.overlap_phase(ds, 1)
    for key in bench._OVERLAP_RATE_KEYS:
        assert out[key] is None, key
    assert "overlap_skipped" in out
    assert out["pp_zb_useful_tick_fraction"] > \
        out["pp_interleaved_useful_tick_fraction"]


# (the degraded-record assertions for the overlap keys ride the
# existing test_degraded_record_keeps_zero_facts_non_null record build
# — one degraded-record construction, not two)


def test_lint_phase_runs_clean_and_fast():
    """r16: the dttlint drill — zero non-baselined findings with the
    checked-in baseline, all eleven rules (DTT009 since r18, DTT010
    since r20, DTT011 since r23), inside the <10s acceptance budget
    (pure ast, no chip)."""
    out = bench.lint_phase()
    assert out["lint_findings_total"] == 0, out
    assert out["lint_stale_suppressions"] == 0
    assert out["lint_rules"] == 11
    assert out["lint_baselined_total"] >= 0
    assert out["lint_time_s"] < 10.0
    assert "lint_error" not in out
    # the degraded-record ride-along is asserted in
    # test_degraded_record_keeps_schedule_facts_non_null (one shared
    # degraded_record build instead of two)


def test_consan_phase_runs_clean_and_fast():
    """r20: the dttsan drill — zero non-baselined findings (stale
    suppressions count as findings here: either way the gate is dirty)
    with the checked-in baseline + thread registry, inside the <15s
    acceptance budget (pure ast, no chip), with the thread/lock census
    non-null."""
    out = bench.consan_phase()
    assert out["consan_findings_total"] == 0, out
    assert out["consan_threads_total"] > 0
    assert out["consan_locks_total"] > 0
    assert out["consan_shared_attrs"] > 0
    assert out["consan_baselined_total"] >= 0
    assert out["consan_time_s"] < 15.0
    assert "consan_error" not in out
    # the degraded-record ride-along is asserted in
    # test_degraded_record_keeps_schedule_facts_non_null (one shared
    # degraded_record build instead of two)


def test_jaxprcheck_phase_proves_the_full_matrix():
    """r18: the dttcheck drill — the comm ledgers and SPMD safety
    machine-proven against the lowered computation for ALL EIGHT modes
    in the phase's own CPU-mesh subprocess, zero findings. Cached per
    process (the degraded record re-emits the same facts free)."""
    out = bench.jaxprcheck_phase()
    assert out["jaxprcheck_findings_total"] == 0, out
    assert out["jaxprcheck_modes_proven"] == 8
    assert out["jaxprcheck_collectives_total"] > 0
    assert out["jaxprcheck_time_s"] is not None
    assert "jaxprcheck_error" not in out
    # the per-process cache: a second call must not pay the subprocess
    t0 = time.perf_counter()
    again = bench.jaxprcheck_phase()
    assert time.perf_counter() - t0 < 1.0
    assert again == out


def test_perfcheck_phase_proves_the_contract():
    """r23: the dttperf drill — the full (mode x model) prediction
    matrix priced and banded against the checked-in records with zero
    non-baselined findings, and the facts non-null (host-only: pure
    Python + eval_shape, no chip). Cached per process like jaxprcheck;
    the degraded record re-emits the same facts free — asserted here
    to spare a full degraded_record build."""
    out = bench.perfcheck_phase()
    assert out["perfcheck_findings_total"] == 0, out
    assert out["perfcheck_scenarios_proven"] >= 13
    assert out["perfcheck_band_pct"] is not None
    assert out["perfcheck_time_s"] is not None
    assert "perfcheck_error" not in out
    # the per-process cache: a second call must not re-pay the matrix
    t0 = time.perf_counter()
    again = bench.perfcheck_phase()
    assert time.perf_counter() - t0 < 1.0
    assert again == out
    # the degraded-record ride-along is asserted in
    # test_degraded_record_keeps_schedule_facts_non_null (one shared
    # degraded_record build instead of two)
