from distributed_tensorflow_tpu.training.schedules import (
    get_schedule,
    schedule_from_flags,
)
from distributed_tensorflow_tpu.training.train_state import (
    TrainState,
    create_train_state,
    make_train_step,
    make_eval_step,
    sgd,
    adam,
    get_optimizer,
)

__all__ = [
    "TrainState",
    "create_train_state",
    "make_train_step",
    "make_eval_step",
    "sgd",
    "adam",
    "get_optimizer",
    "get_schedule",
    "schedule_from_flags",
]
