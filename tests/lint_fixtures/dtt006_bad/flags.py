"""DTT006 violating fixture: a flag no registered validator reads."""


def DEFINE_integer(name, default, help_str=""):
    pass


DEFINE_integer("checked", 1, "covered below")
DEFINE_integer("unchecked", 2, "nobody validates this")


def _validate(values):
    if int(values.get("checked") or 0) < 0:
        raise ValueError("--checked must be >= 0")


FLAGS._register_validator(_validate)  # noqa: F821 — parsed, not run
