#!/usr/bin/env python
"""The fleet table — one line per replica from a live router's
``/metrics`` (or a saved fleet-report JSON file): state, dispatch
share, in-flight, params step, breaker history, and the router's own
retry/hedge budget counters.

The router (serving/router.py) already serves everything as JSON; this
tool is the human rendering — what you glance at mid-incident to see
WHICH replica is ejected, how the traffic spread looks, and whether the
retry budget is absorbing or denying.

Usage:
    python tools/router_report.py http://127.0.0.1:8100
    python tools/router_report.py fleet.json
    python tools/router_report.py http://127.0.0.1:8100 --json

Exit codes: 0 = healthy count >= the router's min_healthy floor;
1 = below the floor (scriptable as a fleet check); 2 = unreachable /
unparseable input.

stdlib-only, no jax, no chip — run it anywhere the router answers.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.error
import urllib.request


def load_fleet(source: str, timeout_s: float = 10.0) -> dict:
    """A fleet report from a router URL (GET /metrics) or a JSON file."""
    if source.startswith(("http://", "https://")) or ":" in source \
            and not os.path.exists(source):
        url = source if "://" in source else f"http://{source}"
        req = urllib.request.Request(url.rstrip("/") + "/metrics",
                                     method="GET")
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return json.loads(r.read().decode())
    with open(source, encoding="utf-8") as f:
        return json.load(f)


def render(fleet: dict) -> str:
    lines = []
    total = sum(r.get("dispatches") or 0
                for r in fleet.get("replicas", ()))
    lines.append(
        f"fleet: {fleet.get('healthy')}/{len(fleet.get('replicas', ()))}"
        f" healthy (floor {fleet.get('min_healthy')}) · "
        f"requests {fleet.get('requests_total')} · "
        f"retries {fleet.get('retries_total')}"
        f" (denied {fleet.get('retries_denied')}) · "
        f"hedges {fleet.get('hedges_total')}"
        f" (wins {fleet.get('hedge_wins')},"
        f" denied {fleet.get('hedges_denied')})")
    header = (f"{'replica':<24} {'state':<9} {'share':>6} {'infl':>5} "
              f"{'queue':>5} {'step':>6} {'fails':>5} {'ejects':>6} "
              f"{'cooldown':>8} {'goodput':>8}")
    lines.append(header)
    lines.append("-" * len(header))
    for rep in fleet.get("replicas", ()):
        share = (100.0 * (rep.get("dispatches") or 0) / total
                 if total else 0.0)
        state = rep.get("state", "?")
        if rep.get("admin_drain"):
            state += "*"  # admin-drained (rolling reload in progress)
        goodput = rep.get("goodput_uptime_pct")
        cooldown = rep.get("eject_cooldown_s") or 0.0
        lines.append(
            f"{rep.get('name', '?'):<24} {state:<9} {share:>5.1f}% "
            f"{rep.get('inflight') or 0:>5} "
            f"{rep.get('queue_depth') if rep.get('queue_depth') is not None else '-':>5} "
            f"{rep.get('params_step') if rep.get('params_step') is not None else '-':>6} "
            f"{rep.get('consecutive_failures') or 0:>5} "
            f"{rep.get('ejections') or 0:>6} "
            f"{cooldown:>7.1f}s "
            f"{f'{goodput:.1f}%' if goodput is not None else '-':>8}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("source", help="router URL (http://host:port) or a "
                                   "saved fleet-report JSON file")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw fleet report JSON instead of "
                         "the table")
    ap.add_argument("--timeout", type=float, default=10.0)
    args = ap.parse_args(argv)
    try:
        fleet = load_fleet(args.source, timeout_s=args.timeout)
    except (OSError, urllib.error.URLError, ValueError) as e:
        print(f"router_report: cannot load {args.source}: {e}",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(fleet, indent=2, default=str))
    else:
        print(render(fleet))
    healthy = fleet.get("healthy")
    floor = fleet.get("min_healthy")
    if healthy is not None and floor is not None and healthy < floor:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
