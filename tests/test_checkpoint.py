"""Checkpoint/restore: atomicity, latest-selection, Supervisor semantics.

Reference behavior under test: chief-only 600s-cadence checkpointing with
auto-restore (MNISTDist.py:154,159-170).
"""

import os
import shutil

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.checkpoint import (
    Checkpointer,
    latest_checkpoint,
    restore_latest,
    save_checkpoint,
)
from distributed_tensorflow_tpu.models import DeepCNN
from distributed_tensorflow_tpu.training import create_train_state, sgd
from distributed_tensorflow_tpu.training.supervisor import Supervisor


def _state():
    return create_train_state(DeepCNN(), sgd(0.01), seed=0)


def test_save_restore_roundtrip(tmp_path):
    state = _state()
    save_checkpoint(str(tmp_path), state, step=7)
    restored, step = restore_latest(str(tmp_path), state)
    assert step == 7
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_latest_picks_newest(tmp_path):
    state = _state()
    save_checkpoint(str(tmp_path), state, step=5)
    save_checkpoint(str(tmp_path), state, step=12)
    path, step = latest_checkpoint(str(tmp_path))
    assert step == 12 and path.endswith("ckpt-12.npz")


def test_gc_max_to_keep(tmp_path):
    state = _state()
    for s in range(8):
        save_checkpoint(str(tmp_path), state, step=s, max_to_keep=3)
    kept = sorted(f for f in os.listdir(tmp_path) if f.endswith(".npz"))
    assert kept == ["ckpt-5.npz", "ckpt-6.npz", "ckpt-7.npz"]


def test_restore_none_when_empty(tmp_path):
    assert restore_latest(str(tmp_path / "nothing"), _state()) is None


def test_torn_index_falls_back_to_files(tmp_path):
    state = _state()
    save_checkpoint(str(tmp_path), state, step=3)
    with open(tmp_path / "checkpoint", "w") as f:
        f.write("{corrupt")
    path, step = latest_checkpoint(str(tmp_path))
    assert step == 3


def test_shape_mismatch_raises(tmp_path):
    state = _state()
    save_checkpoint(str(tmp_path), state, step=1)
    other = create_train_state(DeepCNN(hidden_units=512), sgd(0.01), seed=0)
    with pytest.raises(ValueError, match="shape mismatch"):
        restore_latest(str(tmp_path), other)


def test_checkpointer_chief_only(tmp_path):
    state = _state()
    non_chief = Checkpointer(str(tmp_path), is_chief=False, save_model_secs=0)
    assert non_chief.save(state, 1) is None
    assert not os.listdir(tmp_path)


def test_checkpointer_cadence(tmp_path):
    state = _state()
    ck = Checkpointer(str(tmp_path), is_chief=True, save_model_secs=10_000)
    assert ck.maybe_save(state, 1) is None  # cadence not elapsed
    ck._last_save = 0.0  # force elapsed
    assert ck.maybe_save(state, 2) is not None


def test_supervisor_managed_restores_and_final_saves(tmp_path):
    state = _state()
    sv = Supervisor(is_chief=True, logdir=str(tmp_path), save_model_secs=10_000)
    with sv.managed(state) as box:
        assert box.step == 0
        new_state = state._replace(step=state.step + 5)
        box.update(new_state, 5)
    assert sv.should_stop()
    # a fresh supervisor restores step 5
    sv2 = Supervisor(is_chief=True, logdir=str(tmp_path))
    _, step = sv2.init_or_restore(state)
    assert step == 5


def test_supervisor_saves_on_error(tmp_path):
    state = _state()
    sv = Supervisor(is_chief=True, logdir=str(tmp_path), save_model_secs=10_000)
    with pytest.raises(RuntimeError):
        with sv.managed(state) as box:
            box.update(state, 3)
            raise RuntimeError("worker died")
    assert latest_checkpoint(str(tmp_path))[1] == 3


def test_cross_mode_restore_ps_checkpoint_into_trainstate(tmp_path):
    """SURVEY §7 hard part (d): one checkpoint layout across modes. A
    ps-mode checkpoint ({"params","step"} only) restores into a full
    TrainState run — params and step adopted, optimizer state fresh."""
    state = _state()
    trained_params = jax.tree.map(lambda p: p + 1.0, state.params)
    save_checkpoint(str(tmp_path), {"params": trained_params, "step": 40}, 40)

    sv = Supervisor(is_chief=True, logdir=str(tmp_path), save_model_secs=0)
    restored, step = sv.init_or_restore(state)
    assert step == 40
    assert int(restored.step) == 40
    for a, b in zip(jax.tree.leaves(trained_params),
                    jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # optimizer slots untouched (sgd: empty tuple) and rng kept fresh
    assert restored.opt_state == state.opt_state


def test_cross_mode_restore_trainstate_checkpoint_into_ps_layout(tmp_path):
    """Reverse direction: the ps worker's {"params","step"} template reads
    a full-TrainState checkpoint (extra keys ignored)."""
    state = _state()
    save_checkpoint(str(tmp_path), state._replace(step=jnp.int32(7)), 7)
    blob, step = restore_latest(str(tmp_path),
                                {"params": state.params, "step": 0})
    assert step == 7
    assert int(np.asarray(blob["step"])) == 7
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(blob["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_structural_mismatch_stays_loud(tmp_path):
    """A full-state checkpoint whose non-params layout no longer matches
    the template (e.g. optimizer switched sgd->adam between runs) must NOT
    silently fall back to a params-only restore."""
    from distributed_tensorflow_tpu.training import adam

    save_checkpoint(str(tmp_path), _state(), 5)  # sgd layout on disk
    adam_state = create_train_state(DeepCNN(), adam(1e-3), seed=0)
    sv = Supervisor(is_chief=True, logdir=str(tmp_path), save_model_secs=0)
    with pytest.raises(KeyError, match="opt_state"):
        sv.init_or_restore(adam_state)


# ------------------------------------------------- sharded format (r4)


def test_sharded_checkpoint_roundtrip_mesh_state(tmp_path):
    """save_checkpoint_sharded on mesh-sharded state (single process:
    a 1-shard set) must reassemble to the same flat state through
    restore_latest — model-axis-sharded, replicated, bf16, and host
    leaves all covered."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_tpu.checkpoint import (
        restore_latest,
        save_checkpoint_sharded,
    )
    from distributed_tensorflow_tpu.parallel import make_mesh

    mesh = make_mesh()
    state = {
        "sharded": jax.device_put(
            jnp.arange(32.0).reshape(8, 4),
            NamedSharding(mesh, P("data"))),
        "replicated": jax.device_put(jnp.arange(6.0),
                                     NamedSharding(mesh, P())),
        "bf16": jax.device_put(
            jnp.arange(16.0, dtype=jnp.bfloat16),
            NamedSharding(mesh, P("data"))),
        "host": np.int64(7),
    }
    save_checkpoint_sharded(str(tmp_path), state, step=3)
    template = {
        "sharded": np.zeros((8, 4), np.float32),
        "replicated": np.zeros(6, np.float32),
        "bf16": jnp.zeros(16, jnp.bfloat16),
        "host": np.int64(0),
    }
    restored, step = restore_latest(str(tmp_path), template)
    assert step == 3
    np.testing.assert_array_equal(restored["sharded"],
                                  np.arange(32.0).reshape(8, 4))
    np.testing.assert_array_equal(restored["replicated"], np.arange(6.0))
    np.testing.assert_array_equal(
        np.asarray(restored["bf16"], np.float32), np.arange(16.0))
    assert int(restored["host"]) == 7


def _shard_file(directory, step, p=0):
    """The real path of shard ``p`` at ``step`` — saves stamp an attempt
    nonce into the filename, so tests glob instead of hardcoding."""
    import glob
    hits = sorted(glob.glob(os.path.join(str(directory),
                                         f"ckpt-{step}.shard{p}-of-*.npz")))
    assert hits, f"no shard {p} at step {step} in {directory}"
    return hits[0]


def test_incomplete_sharded_set_never_restores(tmp_path):
    """A step whose shard set is missing a file (a peer died mid-save)
    must be invisible: latest_checkpoint falls back to the newest
    COMPLETE step."""
    import jax.numpy as jnp

    from distributed_tensorflow_tpu.checkpoint import (
        latest_checkpoint,
        save_checkpoint_sharded,
    )

    state = {"w": jnp.arange(4.0)}
    save_checkpoint_sharded(str(tmp_path), state, step=5)
    good = latest_checkpoint(str(tmp_path))
    assert good is not None and good[1] == 5
    # forge an INCOMPLETE 2-shard set at a newer step
    src = _shard_file(tmp_path, 5)
    dst = os.path.join(str(tmp_path), "ckpt-9.shard0-of-2.npz")
    shutil.copy(src, dst)
    found = latest_checkpoint(str(tmp_path))
    assert found is not None and found[1] == 5, found


def test_sharded_gc_and_inspect(tmp_path):
    """GC retains max_to_keep across formats; the inspect CLI reads the
    sharded format through the same load path."""
    import jax.numpy as jnp

    from distributed_tensorflow_tpu.checkpoint import save_checkpoint_sharded
    from distributed_tensorflow_tpu.checkpoint.checkpoint import _all_steps
    from distributed_tensorflow_tpu.checkpoint.inspect import describe

    state = {"params": {"w": jnp.arange(8.0)}, "step": np.int64(0)}
    for s in (1, 2, 3):
        save_checkpoint_sharded(str(tmp_path), state, step=s, max_to_keep=2)
    assert _all_steps(str(tmp_path)) == [2, 3]
    rc = describe(_shard_file(tmp_path, 3), key="params/w")
    assert rc == 0


def test_gc_never_deletes_in_progress_first_save(tmp_path):
    """The race the full-suite run caught: process 0 writes its shard of
    the FIRST-ever save and runs GC before process 1's shard lands. With
    no complete set anywhere, the lone shard is indistinguishable from
    an orphan — GC must leave it (deleting it made every coordinated
    save destroy itself whenever the two writes skewed)."""
    import jax.numpy as jnp

    from distributed_tensorflow_tpu.checkpoint import save_checkpoint_sharded
    from distributed_tensorflow_tpu.checkpoint.checkpoint import _gc

    # forge "p0 wrote its half of a 2-shard set" from a real 1-shard file
    save_checkpoint_sharded(str(tmp_path), {"w": jnp.arange(4.0)}, step=8)
    src = _shard_file(tmp_path, 8)
    half = os.path.join(str(tmp_path), "ckpt-8.shard0-of-2.npz")
    os.replace(src, half)
    _gc(str(tmp_path), max_to_keep=5)  # p0's GC, no complete set exists
    assert os.path.exists(half), "GC deleted an in-progress first save"
    old_orphan = os.path.join(str(tmp_path), "ckpt-1.shard0-of-2.npz")
    shutil.copy(half, old_orphan)
    # once a RESTORABLE step exists, orphans BELOW the horizon go (the
    # coordinated cadence means nobody is still writing an older step);
    # the save itself runs GC
    save_checkpoint_sharded(str(tmp_path), {"w": jnp.arange(4.0)}, step=20)
    assert not os.path.exists(old_orphan)
    assert not os.path.exists(half)


def test_latest_checkpoint_prefers_newest_across_formats(tmp_path):
    """A newer monolithic step beats an older sharded one and vice
    versa — the two formats share one step timeline."""
    import jax.numpy as jnp

    from distributed_tensorflow_tpu.checkpoint import (
        latest_checkpoint,
        save_checkpoint,
        save_checkpoint_sharded,
    )

    save_checkpoint_sharded(str(tmp_path), {"w": jnp.arange(4.0)}, step=3)
    save_checkpoint(str(tmp_path), {"w": jnp.arange(4.0)}, step=7)
    found = latest_checkpoint(str(tmp_path))
    assert found is not None and found[1] == 7
    assert found[0].endswith("ckpt-7.npz")
    save_checkpoint_sharded(str(tmp_path), {"w": jnp.arange(4.0)}, step=9)
    found = latest_checkpoint(str(tmp_path))
    assert found is not None and found[1] == 9
    assert "shard0-of-1" in found[0]

# ---------------------------------------- attempt nonces (ADVICE r4)

def test_mixed_attempt_set_never_assembles(tmp_path):
    """Shards from two save ATTEMPTS at the same (step, n) — a crashed
    save then a restart re-reaching the same step — must never combine
    into a restorable set, even though the (step, n) key matches."""
    import jax.numpy as jnp

    from distributed_tensorflow_tpu.checkpoint import (
        latest_checkpoint,
        save_checkpoint_sharded,
    )
    from distributed_tensorflow_tpu.checkpoint.checkpoint import (
        _sharded_steps,
    )

    save_checkpoint_sharded(str(tmp_path), {"w": jnp.arange(4.0)}, step=3)
    # forge the halves of TWO different 2-shard attempts at step 9:
    # attempt aaaaaaaa has shard 0, attempt bbbbbbbb has shard 1
    src = _shard_file(tmp_path, 3)
    shutil.copy(src, os.path.join(
        str(tmp_path), "ckpt-9.shard0-of-2.aaaaaaaa.npz"))
    shutil.copy(src, os.path.join(
        str(tmp_path), "ckpt-9.shard1-of-2.bbbbbbbb.npz"))
    assert 9 not in _sharded_steps(str(tmp_path))
    found = latest_checkpoint(str(tmp_path))
    assert found is not None and found[1] == 3, found


def test_two_complete_attempts_newest_wins(tmp_path):
    """When a step somehow holds two COMPLETE sets (re-save after a
    restore, both attempts finished), the most recently written attempt
    is the one restored — never a mix."""
    import jax.numpy as jnp
    import time as _time

    from distributed_tensorflow_tpu.checkpoint import save_checkpoint_sharded
    from distributed_tensorflow_tpu.checkpoint.checkpoint import (
        load_flat_sharded,
    )

    save_checkpoint_sharded(str(tmp_path), {"w": jnp.zeros(4)}, step=5,
                            attempt="aaaaaaaa")
    _time.sleep(0.05)  # distinct mtimes
    save_checkpoint_sharded(str(tmp_path), {"w": jnp.ones(4)}, step=5,
                            attempt="bbbbbbbb")
    flat = load_flat_sharded(str(tmp_path), 5)
    np.testing.assert_array_equal(flat["w"], np.ones(4, np.float32))


def test_explicit_attempt_lands_in_filename(tmp_path):
    import jax.numpy as jnp

    from distributed_tensorflow_tpu.checkpoint import save_checkpoint_sharded

    path = save_checkpoint_sharded(str(tmp_path), {"w": jnp.arange(2.0)},
                                   step=1, attempt="deadbeef")
    assert path.endswith("ckpt-1.shard0-of-1.deadbeef.npz")
    assert os.path.exists(path)


def test_nonceless_legacy_shards_still_restore(tmp_path):
    """Pre-nonce shard files (no attempt suffix) remain a complete,
    restorable set — the format change is backward compatible."""
    import jax.numpy as jnp

    from distributed_tensorflow_tpu.checkpoint import (
        restore_latest,
        save_checkpoint_sharded,
    )

    real = save_checkpoint_sharded(str(tmp_path), {"w": jnp.arange(4.0)},
                                   step=2)
    legacy = os.path.join(str(tmp_path), "ckpt-2.shard0-of-1.npz")
    os.replace(real, legacy)
    out = restore_latest(str(tmp_path), {"w": np.zeros(4, np.float32)})
    assert out is not None and out[1] == 2
    np.testing.assert_array_equal(out[0]["w"], np.arange(4.0, dtype=np.float32))


def test_overlapping_entries_rejected(tmp_path):
    """load_flat_sharded's coverage check is positional (ADVICE r4): an
    overlap plus a gap that sums to the right element count must fail."""
    import json as _json

    import jax.numpy as jnp

    from distributed_tensorflow_tpu.checkpoint import save_checkpoint_sharded
    from distributed_tensorflow_tpu.checkpoint.checkpoint import (
        _SHARDMETA,
        load_flat_sharded,
    )

    path = save_checkpoint_sharded(str(tmp_path), {"w": jnp.arange(4.0)},
                                   step=1, attempt="cafecafe")
    with np.load(path) as z:
        meta = _json.loads(bytes(z[_SHARDMETA]).decode())
        arrays = {k: z[k] for k in z.files if k != _SHARDMETA}
    # duplicate the sole entry, then shrink both to half the leaf: two
    # overlapping [0:2] slices cover 4 elements total but leave [2:4]
    # as a gap — the old element-count check passed this
    (e,) = meta["leaves"]["w"]["entries"]
    e2 = dict(e, npz="w@1")
    e["index"] = [[0, 2]]
    e2["index"] = [[0, 2]]
    meta["leaves"]["w"]["entries"] = [e, e2]
    arrays["w@1"] = arrays[e["npz"]][:2].copy()
    arrays[e["npz"]] = arrays[e["npz"]][:2].copy()
    # keep the r8 CRC manifest consistent with the forged arrays so the
    # POSITIONAL coverage check (not the checksum) is what trips
    from distributed_tensorflow_tpu.utils.events import crc32c as _crc

    meta["crc32c"] = {k: _crc(np.ascontiguousarray(v))
                      for k, v in arrays.items()}
    arrays[_SHARDMETA] = np.frombuffer(
        _json.dumps(meta).encode(), dtype=np.uint8)
    np.savez(path, **arrays)
    with pytest.raises(ValueError, match="overlap"):
        load_flat_sharded(str(tmp_path), 1)


def test_checkpoint_keys_raises_on_vanished_set(tmp_path):
    """A shard path whose set disappeared (racing peer GC) must raise,
    not return an empty key set that flips template decisions
    (ADVICE r4)."""
    from distributed_tensorflow_tpu.checkpoint.checkpoint import (
        checkpoint_keys,
    )

    ghost = os.path.join(str(tmp_path), "ckpt-4.shard0-of-2.abcdabcd.npz")
    with pytest.raises(FileNotFoundError):
        checkpoint_keys(ghost)


def test_invalid_attempt_token_rejected(tmp_path):
    """A token the scan regex can't parse would be silently unrestorable
    AND invisible to GC — the save must refuse it up front."""
    import jax.numpy as jnp

    from distributed_tensorflow_tpu.checkpoint import save_checkpoint_sharded

    for bad in ("ABCD1234", "xyz", "deadbeef0", "dead-bee"):
        with pytest.raises(ValueError, match="8 lowercase hex"):
            save_checkpoint_sharded(str(tmp_path), {"w": jnp.zeros(2)},
                                    step=1, attempt=bad)


def test_default_attempt_is_collective_free_and_single_process_noncing(
        tmp_path):
    """attempt=None single-process: a fresh valid nonce per save (no
    collective exists to agree one — and none must: the supervisor exit
    path runs the sharded save unbounded)."""
    import jax.numpy as jnp

    from distributed_tensorflow_tpu.checkpoint import save_checkpoint_sharded
    from distributed_tensorflow_tpu.checkpoint.checkpoint import _SHARD_RE

    p1 = save_checkpoint_sharded(str(tmp_path), {"w": jnp.zeros(2)}, step=1)
    p2 = save_checkpoint_sharded(str(tmp_path), {"w": jnp.zeros(2)}, step=2)
    m1 = _SHARD_RE.fullmatch(os.path.basename(p1))
    m2 = _SHARD_RE.fullmatch(os.path.basename(p2))
    assert m1 and m2 and m1.group(4) and m2.group(4)
    assert m1.group(4) != m2.group(4)


def test_exit_agreement_carries_attempt_token():
    """agree_clean_exit(return_token=True): verdict True comes with an
    8-hex token (single-process: process 0's own draw); a failed verdict
    carries None."""
    from distributed_tensorflow_tpu.checkpoint.checkpoint import _ATTEMPT_RE
    from distributed_tensorflow_tpu.utils.pytree import agree_clean_exit

    verdict, token = agree_clean_exit(True, timeout_s=30.0,
                                      return_token=True)
    assert verdict is True and _ATTEMPT_RE.fullmatch(token)
    verdict, token = agree_clean_exit(False, timeout_s=30.0,
                                      return_token=True)
    assert verdict is False and token is None
    # the 1-arg form is unchanged for existing callers
    assert agree_clean_exit(True, timeout_s=30.0) is True


def test_restore_rescans_when_sharded_set_vanishes_midway(tmp_path,
                                                          monkeypatch):
    """A sharded set that was complete at selection time can vanish
    between latest_checkpoint and the read (racing peer GC) —
    checkpoint_keys/load_flat_sharded raise FileNotFoundError. The
    Supervisor must degrade to a RE-SCAN (picking the newest older
    complete checkpoint), not crash the restore (advisor-low
    supervisor.py)."""
    import distributed_tensorflow_tpu.checkpoint.checkpoint as ckpt_mod

    state = _state()
    save_checkpoint(str(tmp_path), state, step=3)
    save_checkpoint(str(tmp_path), state, step=7)

    real_load = ckpt_mod.load_flat
    raced = {"n": 0}

    def racing_load(path):
        if path.endswith("ckpt-7.npz") and raced["n"] == 0:
            # the set vanishes under the reader exactly once
            raced["n"] += 1
            os.unlink(path)
            raise FileNotFoundError(
                f"sharded checkpoint set for {path!r} is no longer "
                f"complete")
        return real_load(path)

    monkeypatch.setattr(ckpt_mod, "load_flat", racing_load)
    sv = Supervisor(is_chief=True, logdir=str(tmp_path),
                    save_model_secs=10_000)
    restored, step = sv.init_or_restore(state)
    assert raced["n"] == 1
    assert step == 3  # fell back to the older complete checkpoint
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
