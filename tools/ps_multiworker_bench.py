"""Multi-worker async PS measurement: fan-in, cycle scaling, staleness.

The reference's deployment is N worker PROCESSES hammering the ps
(MNISTDist.py:94-95,188); this measures how this build's PS emulation
behaves as worker count grows — with real processes (r5: the r4
version used threads, which confounded per-worker rates with the GIL
and host compute contention; worker processes isolate what the ps
actually serializes). Compute runs on CPU (forced — the object of
measurement is the ps fan-in, dedup table, and the mirror
desync/resync protocol under contention, not chip throughput; CPU also
keeps the shared TPU chip clean). Each worker process owns a PSClient
(own sockets + client id) driving MirrorCycle in the documented
multi-worker degraded mode: every foreign push desyncs the mirror,
forcing a resync pull — the reference's staleness model.

Per N in {1, 2, 4, 8}: aggregate pushes/s, per-worker cycle rate, the
observed STALENESS distribution (per push: how many foreign pushes
landed since this worker's mirror state — ``new_step - my_step - 1``),
and the exactly-once check (global step total == N * cycles: no push
lost, none double-applied, under full contention). Prints one JSON
line per N.

Start protocol: workers print READY after connecting + initial sync,
the parent touches a go-file once all are ready, workers spin on it —
so the timed windows overlap maximally without shared-memory
primitives.

Usage: python tools/ps_multiworker_bench.py [cycles_per_worker]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

# runnable as `python tools/ps_multiworker_bench.py` from anywhere:
# sys.path[0] is tools/, the package root is one level up
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

BATCH = 64


def worker_main(widx: int, n_workers: int, address: str, cycles: int,
                gofile: str) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from distributed_tensorflow_tpu.data import read_data_sets
    from distributed_tensorflow_tpu.models import get_model
    from distributed_tensorflow_tpu.parallel.ps_emulation import (
        MirrorCycle,
        PSClient,
        assign_shards,
        flatten_params,
        make_grad_fn,
    )

    ds = read_data_sets("", dataset="mnist")
    model = get_model("mlp", hidden_units=100)
    template = model.init(jax.random.PRNGKey(0))
    assignment = assign_shards(list(flatten_params(template)), 1)
    grad_fn = make_grad_fn(model, keep_prob=1.0, devices=jax.devices()[:1])
    client = PSClient([address])
    data = ds.train.shard(widx, n_workers)
    cyc = MirrorCycle(client, grad_fn, template, assignment,
                      learning_rate=0.01, resync_steps=10**9)
    cyc.maybe_sync()
    rng = jax.random.PRNGKey(widx)
    print("READY", flush=True)
    while not os.path.exists(gofile):
        time.sleep(0.005)
    staleness: list[int] = []
    desyncs = 0
    t0 = time.perf_counter()
    for i in range(cycles):
        before = cyc.step
        cyc.run_cycle(data.next_batch(BATCH), jax.random.fold_in(rng, i))
        if cyc.step > before:  # a push happened this cycle
            staleness.append(cyc.step - before - 1)
        if cyc.needs_resync:
            desyncs += 1
            cyc.maybe_sync()
    cyc.drain()
    dt = time.perf_counter() - t0
    client.close()
    print(json.dumps({"widx": widx, "dt": dt, "staleness": staleness,
                      "desyncs": desyncs}), flush=True)


def _spawn_worker(widx: int, n: int, address: str, cycles: int,
                  gofile: str, errdir: str):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (env.get("PYTHONPATH", ""), _REPO_ROOT) if p)
    # stderr goes to a FILE, not a pipe: a crashing worker can dump
    # >64KB of logging+traceback, and an undrained stderr pipe would
    # block its write -> stdout never reaches EOF -> parent deadlocks
    err_path = os.path.join(errdir, f"worker{widx}.err")
    errf = open(err_path, "w")
    p = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker", str(widx),
         str(n), address, str(cycles), gofile],
        stdout=subprocess.PIPE, stderr=errf, text=True, env=env)
    p.err_path = err_path  # type: ignore[attr-defined]
    errf.close()  # the child holds the fd
    return p


def _err_tail(p, limit: int = 500) -> str:
    try:
        with open(p.err_path) as f:
            return f.read()[-limit:]
    except OSError:
        return "<no stderr captured>"


def main(cycles: int = 60):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from distributed_tensorflow_tpu.models import get_model
    from distributed_tensorflow_tpu.parallel.ps_emulation import (
        PSClient,
        PSServer,
        assign_shards,
        flatten_params,
    )

    model = get_model("mlp", hidden_units=100)
    flat = flatten_params(model.init(jax.random.PRNGKey(0)))

    for n_workers in (1, 2, 4, 8):
        server = PSServer(0, "127.0.0.1:0")
        server.start_background()
        init_client = PSClient([server.address])
        assignment = assign_shards(list(flat), 1)
        init_client.init_params(flat, assignment, optimizer="sgd",
                                learning_rate=0.01, num_workers=n_workers)
        tmp = tempfile.mkdtemp(prefix="psbench-")
        gofile = os.path.join(tmp, "go")
        procs = []
        try:
            import threading

            procs = [_spawn_worker(w, n_workers, server.address, cycles,
                                   gofile, tmp) for w in range(n_workers)]
            results = []
            errors = []
            for p in procs:
                # bound the READY wait: a worker wedged in init would
                # otherwise block this readline forever. The killer
                # makes readline return EOF ("") instead. A worker
                # dying here is a per-N error record, not an abort —
                # the remaining N still get measured.
                killer = threading.Timer(300.0, p.kill)
                killer.start()
                try:
                    while True:  # skip stray library chatter on stdout
                        line = p.stdout.readline()
                        if line == "":
                            errors.append(f"worker died/hung before "
                                          f"READY: {_err_tail(p)}")
                            break
                        if line.strip() == "READY":
                            break
                finally:
                    killer.cancel()
            if not errors:
                with open(gofile, "w"):
                    pass
                for p in procs:
                    try:
                        out, _ = p.communicate(timeout=600)
                    except subprocess.TimeoutExpired:
                        p.kill()
                        out, _ = p.communicate()
                        errors.append(f"worker timed out: {_err_tail(p)}")
                        continue
                    if p.returncode != 0:
                        errors.append(_err_tail(p))
                        continue
                    try:
                        results.append(
                            json.loads(out.strip().splitlines()[-1]))
                    except (ValueError, IndexError):
                        errors.append(f"worker emitted no result JSON "
                                      f"(stdout {out[-200:]!r}); "
                                      f"{_err_tail(p)}")
            if errors:
                print(json.dumps({"n_workers": n_workers,
                                  "errors": errors}), flush=True)
                continue

            total = server.dispatch({"op": "get_step"})["global_step"]
            st = np.array(sum((r["staleness"] for r in results), []))
            wall = max(r["dt"] for r in results)
            rec = {
                "n_workers": n_workers,
                "workers": "processes",
                "global_step_total": int(total),
                "pushes_expected": n_workers * cycles,
                "exactly_once": int(total) == n_workers * cycles,
                "aggregate_pushes_per_sec": round(total / wall, 2),
                "per_worker_cycles_per_sec": sorted(
                    round(cycles / r["dt"], 2) for r in results),
                "desyncs_total": int(sum(r["desyncs"] for r in results)),
                "staleness_mean": (round(float(st.mean()), 3)
                                   if len(st) else 0),
                "staleness_p95": (int(np.percentile(st, 95))
                                  if len(st) else 0),
                "staleness_max": int(st.max()) if len(st) else 0,
            }
            print(json.dumps(rec), flush=True)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            init_client.close()
            server.close()
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)  # go-file + .err files


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        worker_main(int(sys.argv[2]), int(sys.argv[3]), sys.argv[4],
                    int(sys.argv[5]), sys.argv[6])
    else:
        main(int(sys.argv[1]) if len(sys.argv) > 1 else 60)
