"""Shared pytree <-> path-keyed-dict conversion.

One implementation used by both the checkpoint writer and the PS-emulation
wire protocol, so the key scheme and dtype handling cannot drift between
them. Keys are '/'-joined tree paths ("weights/wd1"); bfloat16 leaves are
tagged and viewed as uint16 for serializers that can't store bf16 (npz).
"""

from __future__ import annotations

import jax
import numpy as np

_BF16_TAG = "__bf16__"


def _path_str(p) -> str:
    for attr in ("key", "idx", "name"):
        if hasattr(p, attr):
            return str(getattr(p, attr))
    return str(p)


def path_key(path) -> str:
    return "/".join(_path_str(p) for p in path)


def flatten_pytree(tree, *, tag_bf16: bool = False) -> dict[str, np.ndarray]:
    """Pytree -> {path_key: np.ndarray}. With ``tag_bf16``, bfloat16 leaves
    are stored as uint16 views under a tagged key (npz-safe)."""
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = path_key(path)
        arr = np.asarray(jax.device_get(leaf))
        if tag_bf16 and arr.dtype == jax.numpy.bfloat16:
            flat[_BF16_TAG + key] = arr.view(np.uint16)
        else:
            flat[key] = arr
    return flat


def unflatten_pytree(template, flat: dict[str, np.ndarray], *, check_shapes: bool = True):
    """{path_key: array} -> pytree with ``template``'s structure.

    Raises KeyError on missing keys and ValueError on shape mismatch (when
    ``check_shapes``); casts to the template leaf dtype."""
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths_leaves:
        key = path_key(path)
        if key in flat:
            arr = flat[key]
        elif _BF16_TAG + key in flat:
            arr = flat[_BF16_TAG + key].view(jax.numpy.bfloat16)
        else:
            raise KeyError(f"missing array for {key!r}")
        leaf_arr = np.asarray(leaf)
        if check_shapes and tuple(arr.shape) != tuple(leaf_arr.shape):
            raise ValueError(
                f"shape mismatch at {key!r}: got {arr.shape}, "
                f"expected {leaf_arr.shape}"
            )
        if arr.dtype != leaf_arr.dtype:
            arr = arr.astype(leaf_arr.dtype)
        leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, leaves)
